"""D&C tridiagonal eigensolver tests
(reference: test/unit/eigensolver/test_tridiag_solver.cpp): residual +
orthogonality checks against scipy over sizes, leaf sizes, and pathological
inputs (clustered eigenvalues, zero couplings, constant diagonal).
"""

import numpy as np
import pytest
import scipy.linalg as sla

from dlaf_tpu.eigensolver.tridiag_solver import tridiag_solver


def check(d, e, lam, q, tol=5e-13):
    n = d.shape[0]
    t = np.diag(d) + np.diag(e, 1) + np.diag(e, -1)
    scale = max(np.abs(d).max(initial=1.0), np.abs(e).max(initial=1.0), 1.0)
    # eigenvalues vs scipy
    w = sla.eigvalsh_tridiagonal(d, e) if n > 1 else d
    np.testing.assert_allclose(lam, w, atol=tol * scale * n, rtol=1e-12)
    # residual and orthogonality
    assert np.linalg.norm(t @ q - q * lam[None, :]) < tol * scale * n * 10
    assert np.linalg.norm(q.T @ q - np.eye(n)) < tol * n * 10


@pytest.mark.parametrize("n,nb", [(4, 2), (16, 4), (33, 8), (64, 8), (100, 16),
                                  (65, 64), (7, 2)])
def test_random(n, nb):
    rng = np.random.default_rng(n)
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    lam, q = tridiag_solver(d, e, nb, use_device=False)
    check(d, e, lam, q)


def test_zero_coupling():
    rng = np.random.default_rng(1)
    d = rng.standard_normal(32)
    e = rng.standard_normal(31)
    e[15] = 0.0  # exact decoupling at the split point
    lam, q = tridiag_solver(d, e, 16, use_device=False)
    check(d, e, lam, q)


def test_constant_diagonal_heavy_deflation():
    n = 48
    d = np.full(n, 2.0)
    e = np.full(n - 1, 1.0)  # Toeplitz: known eigenvalues, many near-equal poles
    lam, q = tridiag_solver(d, e, 8, use_device=False)
    expect = 2.0 + 2.0 * np.cos(np.pi * np.arange(n, 0, -1) / (n + 1))
    np.testing.assert_allclose(lam, np.sort(expect), atol=1e-12)
    check(d, e, lam, q)


def test_clustered_eigenvalues():
    rng = np.random.default_rng(3)
    n = 40
    d = np.ones(n) + 1e-14 * rng.standard_normal(n)
    e = 1e-13 * np.abs(rng.standard_normal(n - 1))
    lam, q = tridiag_solver(d, e, 8, use_device=False)
    check(d, e, lam, q)


def test_wilkinson():
    # Wilkinson W21+: famously paired close eigenvalues
    m = 10
    d = np.abs(np.arange(-m, m + 1)).astype(np.float64)
    e = np.ones(2 * m)
    lam, q = tridiag_solver(d, e, 4, use_device=False)
    check(d, e, lam, q)


def test_device_path_matches():
    rng = np.random.default_rng(9)
    d = rng.standard_normal(24)
    e = rng.standard_normal(23)
    l1, q1 = tridiag_solver(d, e, 8, use_device=False)
    l2, q2 = tridiag_solver(d, e, 8, use_device=True)
    np.testing.assert_allclose(l1, l2, atol=1e-12)
    np.testing.assert_allclose(np.abs(q1), np.abs(q2), atol=1e-10)


def test_mesh_sharded_merge_tree(monkeypatch, devices8):
    """tridiag_solver(mesh=...): merge gemms run sharded over the 2D mesh
    and the returned eigenvector matrix is 2D-sharded (the beyond-reference
    scaling path for Q past one device's HBM); results match the host
    reference twin."""
    from jax.sharding import NamedSharding

    import importlib

    ts_mod = importlib.import_module("dlaf_tpu.eigensolver.tridiag_solver")
    from dlaf_tpu.comm.grid import Grid

    rng = np.random.default_rng(77)
    n = 96
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    # drop the threshold so several tree levels actually shard in-test
    monkeypatch.setattr(ts_mod, "_SHARD_MERGE_MIN_N", 48)
    mesh = Grid(2, 4).mesh
    lam, q = ts_mod.tridiag_solver(d, e, 16, use_device=True, mesh=mesh)
    assert isinstance(q.sharding, NamedSharding)
    assert q.sharding.mesh == mesh
    l_ref, _ = ts_mod.tridiag_solver(d, e, 16, use_device=False)
    np.testing.assert_allclose(lam, l_ref, atol=1e-11)
    check(d, e, lam, np.asarray(q))
    # sharded merge + sharded DEVICE secular branch together
    import dlaf_tpu.config as config

    monkeypatch.setenv("DLAF_SECULAR_DEVICE_MIN_K", "1")
    config.initialize()
    try:
        lam2, q2 = ts_mod.tridiag_solver(d, e, 16, use_device=True, mesh=mesh)
    finally:
        monkeypatch.delenv("DLAF_SECULAR_DEVICE_MIN_K")
        config.initialize()
    np.testing.assert_allclose(lam2, l_ref, atol=1e-11)
    check(d, e, lam2, np.asarray(q2))


def _run_with_batch(monkeypatch, dcb, fn):
    import dlaf_tpu.config as config

    monkeypatch.setenv("DLAF_DC_LEVEL_BATCH", dcb)
    config.initialize()
    try:
        return fn()
    finally:
        monkeypatch.delenv("DLAF_DC_LEVEL_BATCH", raising=False)
        config.initialize()


@pytest.mark.parametrize("use_device", [True, False])
@pytest.mark.parametrize("n,nb", [(96, 16), (100, 16), (64, 8), (33, 8)])
def test_level_batched_matches_serialized(n, nb, use_device, monkeypatch):
    """dc_level_batch=1 (vmapped same-shape merge groups per tree level)
    must reproduce the serialized walk BITWISE on the host-secular route:
    the per-merge host control work is identical, the vmapped assembly
    scatters/rotations/gathers are lane-exact, and the batched Q·C
    dot_general contracts the same K extent per lane
    (docs/eigensolver_perf.md bitwise contract)."""
    rng = np.random.default_rng(n + nb)
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    l0, q0 = _run_with_batch(monkeypatch, "0",
                             lambda: tridiag_solver(d, e, nb, use_device))
    l1, q1 = _run_with_batch(monkeypatch, "1",
                             lambda: tridiag_solver(d, e, nb, use_device))
    np.testing.assert_array_equal(l1, l0)
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q0))
    check(d, e, l1, np.asarray(q1))


def test_level_batched_device_secular(monkeypatch):
    """Batched vs serialized with the DEVICE secular branch forced: the
    batch re-buckets each lane to the group's max k, whose padded zero
    terms may reassociate at <= 1 ulp (the one documented exception,
    docs/eigensolver_perf.md) — results must stay eigensolver-grade and
    match the serialized walk to ulp-level."""
    import dlaf_tpu.config as config

    rng = np.random.default_rng(10)
    n = 96
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    monkeypatch.setenv("DLAF_SECULAR_DEVICE_MIN_K", "1")
    l0, q0 = _run_with_batch(monkeypatch, "0",
                             lambda: tridiag_solver(d, e, 16, True))
    l1, q1 = _run_with_batch(monkeypatch, "1",
                             lambda: tridiag_solver(d, e, 16, True))
    monkeypatch.delenv("DLAF_SECULAR_DEVICE_MIN_K")
    config.initialize()
    np.testing.assert_allclose(l1, l0, rtol=1e-14, atol=1e-14)
    check(d, e, l1, np.asarray(q1))


def test_level_batched_mesh_sharded(monkeypatch, devices8):
    """Level batching under a mesh: merges past _SHARD_MERGE_MIN_N keep
    the per-merge sharded path (batch groups never shard), and the full
    decomposition still matches the host reference."""
    import importlib

    ts_mod = importlib.import_module("dlaf_tpu.eigensolver.tridiag_solver")
    from dlaf_tpu.comm.grid import Grid

    rng = np.random.default_rng(21)
    n = 96
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    monkeypatch.setattr(ts_mod, "_SHARD_MERGE_MIN_N", 48)
    mesh = Grid(2, 4).mesh
    lam, q = _run_with_batch(
        monkeypatch, "1",
        lambda: ts_mod.tridiag_solver(d, e, 16, True, mesh=mesh))
    l_ref, _ = _run_with_batch(
        monkeypatch, "0", lambda: ts_mod.tridiag_solver(d, e, 16, False))
    np.testing.assert_allclose(lam, l_ref, atol=1e-11)
    check(d, e, lam, np.asarray(q))


def test_level_batched_mxu_route(monkeypatch):
    """Batched apply under f64_gemm="mxu" — the combination every TPU run
    gets by default (both knobs auto-resolve on there): the ozaki int8
    reroute must vmap cleanly and stay bitwise vs the serialized mxu
    walk."""
    import dlaf_tpu.config as config

    rng = np.random.default_rng(3)
    n = 64
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
    monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "4")
    try:
        l0, q0 = _run_with_batch(monkeypatch, "0",
                                 lambda: tridiag_solver(d, e, 8, True))
        l1, q1 = _run_with_batch(monkeypatch, "1",
                                 lambda: tridiag_solver(d, e, 8, True))
    finally:
        monkeypatch.delenv("DLAF_F64_GEMM")
        monkeypatch.delenv("DLAF_F64_GEMM_MIN_DIM")
        config.initialize()
    np.testing.assert_array_equal(l1, l0)
    np.testing.assert_array_equal(np.asarray(q1), np.asarray(q0))
    check(d, e, l1, np.asarray(q1))


def test_level_batched_counters(monkeypatch, tmp_path):
    """dlaf_dc_merges_total accounting: a batched run books both modes
    (vmapped groups + the serialized top merges), a serialized run books
    only mode=serialized."""
    import dlaf_tpu.config as config
    from dlaf_tpu import obs

    rng = np.random.default_rng(3)
    d = rng.standard_normal(96)
    e = rng.standard_normal(95)
    monkeypatch.setenv("DLAF_METRICS_PATH", str(tmp_path / "dc.jsonl"))

    def modes(dcb):
        obs._reset_for_tests()
        out = _run_with_batch(monkeypatch, dcb,
                              lambda: tridiag_solver(d, e, 16, True))
        assert out is not None
        snap = obs.registry().snapshot()
        return {m["labels"]["mode"]: m["value"] for m in snap
                if m["name"] == "dlaf_dc_merges_total"}

    try:
        m1 = modes("1")
        assert m1.get("batched", 0) > 0 and m1.get("serialized", 0) > 0, m1
        m0 = modes("0")
        assert m0.get("batched", 0) == 0 and m0.get("serialized", 0) > 0, m0
    finally:
        monkeypatch.delenv("DLAF_METRICS_PATH")
        config.initialize()
        obs._reset_for_tests()


def test_native_secular_matches_numpy():
    """C++ safeguarded-Newton secular solver vs the numpy bisection: same
    anchors, same roots, and the roots actually satisfy the secular eq."""
    from dlaf_tpu.eigensolver.tridiag_solver import _secular_roots
    from dlaf_tpu.native import bindings

    rng = np.random.default_rng(4)
    for k in (1, 2, 7, 129, 500):
        ds = np.sort(rng.standard_normal(k)) * 3
        # enforce the post-deflation gap so poles are distinct
        ds += np.arange(k) * 1e-6
        zs = rng.standard_normal(k)
        zs[np.abs(zs) < 0.05] = 0.05
        zs /= np.linalg.norm(zs)
        rho = abs(rng.standard_normal()) + 0.5
        a_np, mu_np = _secular_roots(ds, zs, rho)
        a_nat, mu_nat = bindings.secular_roots(ds, zs, rho)
        lam_np = ds[a_np] + mu_np
        lam_nat = ds[a_nat] + mu_nat
        scale = np.abs(ds).max() + rho
        np.testing.assert_allclose(lam_nat, lam_np, atol=1e-11 * scale)
        # residual of the secular equation at the native roots
        f = 1.0 + rho * (zs[None, :] ** 2 /
                         ((ds[None, :] - ds[a_nat][:, None]) - mu_nat[:, None])).sum(1)
        fprime = rho * (zs[None, :] ** 2 /
                        ((ds[None, :] - ds[a_nat][:, None]) - mu_nat[:, None]) ** 2).sum(1)
        # |f| should be ~eps * f' * ulp-level root error
        assert np.all(np.abs(f) < 1e-6 * np.maximum(fprime * scale * 1e-10, 1.0) + 1e-7)


def test_native_secular_threads_bitwise():
    """The native secular solver's worker threading (``std::thread`` across
    roots) must give BYTEWISE the single-thread result at a forced count:
    every root is solved independently from read-only inputs, so no
    reduction order can change. Forced nthreads=4 on small k also covers
    the k < min_per_thread regime the auto policy never threads."""
    from dlaf_tpu.native import bindings

    try:
        bindings.get_lib()
    except Exception:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(11)
    for k in (3, 64, 257, 1000):
        ds = np.sort(rng.standard_normal(k)) * 3 + np.arange(k) * 1e-6
        zs = rng.standard_normal(k)
        zs[np.abs(zs) < 0.05] = 0.05
        zs /= np.linalg.norm(zs)
        rho = abs(rng.standard_normal()) + 0.5
        a1, mu1 = bindings.secular_roots(ds, zs, rho, nthreads=1)
        a4, mu4 = bindings.secular_roots(ds, zs, rho, nthreads=4)
        np.testing.assert_array_equal(a4, a1)
        assert mu4.tobytes() == mu1.tobytes()


def test_native_deflate_scan_matches_python(monkeypatch):
    """C++ deflation scan (deflate.cpp) vs the Python fallback loop: same
    rotations, same mutated z/liveness — on data engineered for chained
    near-equal poles and interleaved dead entries."""
    import dlaf_tpu.config as config
    from dlaf_tpu.eigensolver.tridiag_solver import _deflation_scan
    from dlaf_tpu.native import bindings

    rng = np.random.default_rng(5)
    for trial in range(6):
        n = 257
        # clusters: quantized poles produce runs of gap <= tol
        ds = np.sort(np.round(rng.standard_normal(n), 1))
        zs = rng.standard_normal(n) / np.sqrt(n)
        live = np.abs(zs) > rng.uniform(0.01, 0.06)
        tol = 10.0 ** rng.integers(-12, -1)
        z_nat, live_nat = zs.copy(), live.copy()
        out_nat = bindings.deflate_scan(ds, z_nat, live_nat, tol)
        monkeypatch.setenv("DLAF_SECULAR_IMPL", "numpy")
        config.initialize()
        z_py, live_py = zs.copy(), live.copy()
        out_py = _deflation_scan(ds, z_py, live_py, tol)
        monkeypatch.delenv("DLAF_SECULAR_IMPL")
        config.initialize()
        for a, b in zip(out_nat, out_py):
            np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(z_nat, z_py)
        np.testing.assert_array_equal(live_nat, live_py)


def test_device_path_host_memory_stays_linear(monkeypatch):
    """Device merges above the device-secular threshold must not allocate
    O(n^2) host numpy workspaces (round-1 review item 4: u_sorted/qc were
    host (n, n) arrays): intercept np.zeros/np.empty/np.eye during a
    device-path solve with the device-secular branch forced and assert no
    2D host allocation at the merge size appears. (Below the threshold the
    host secular solve legitimately builds (k, k) with k bounded by
    ``secular_device_min_k`` — a constant, not O(n).)"""
    import dlaf_tpu.config as config

    big = []
    n = 96
    real_zeros, real_empty, real_eye = np.zeros, np.empty, np.eye

    def spy(real):
        def wrapped(shape, *a, **k):
            s = shape if isinstance(shape, tuple) else (shape,)
            if len(s) == 2 and min(s) >= n // 2:
                big.append(s)
            return real(shape, *a, **k)
        return wrapped

    def spy_eye(real):
        # np.eye's first argument is a scalar N (allocation is (N, M or N))
        def wrapped(N, M=None, *a, **k):
            if min(N, M if M is not None else N) >= n // 2:
                big.append((N, M if M is not None else N))
            return real(N, M, *a, **k)
        return wrapped

    rng = np.random.default_rng(17)
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    monkeypatch.setenv("DLAF_SECULAR_DEVICE_MIN_K", "1")
    config.initialize()
    try:
        monkeypatch.setattr(np, "zeros", spy(real_zeros))
        monkeypatch.setattr(np, "empty", spy(real_empty))
        monkeypatch.setattr(np, "eye", spy_eye(real_eye))
        lam, q = tridiag_solver(d, e, 16, use_device=True)
        monkeypatch.undo()
    finally:
        config.initialize()
    assert big == [], f"host O(n^2) merge workspaces allocated: {big}"
    check(d, e, lam, np.asarray(q))


def test_secular_impl_config(monkeypatch):
    """The secular_impl knob selects the native path and both give the same
    full decomposition."""
    import dlaf_tpu.config as config

    rng = np.random.default_rng(12)
    d = rng.standard_normal(48)
    e = rng.standard_normal(47)
    monkeypatch.setenv("DLAF_SECULAR_IMPL", "numpy")
    config.initialize()
    l1, _ = tridiag_solver(d, e, 8, use_device=False)
    monkeypatch.setenv("DLAF_SECULAR_IMPL", "native")
    config.initialize()
    l2, q2 = tridiag_solver(d, e, 8, use_device=False)
    monkeypatch.delenv("DLAF_SECULAR_IMPL")
    config.initialize()
    np.testing.assert_allclose(l1, l2, atol=1e-11)
    check(d, e, l2, q2)


def test_device_secular_path(monkeypatch):
    """Force the device secular/refinement branch (used for big merges) and
    check it reproduces the host branch + a correct decomposition."""
    import importlib

    ts_mod = importlib.import_module("dlaf_tpu.eigensolver.tridiag_solver")

    import dlaf_tpu.config as config

    rng = np.random.default_rng(10)
    n = 64
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    l_host, _ = tridiag_solver(d, e, 16, use_device=False)
    monkeypatch.setenv("DLAF_SECULAR_DEVICE_MIN_K", "1")
    config.initialize()
    assert ts_mod._device_secular_min_k() == 1
    lam, q = tridiag_solver(d, e, 16, use_device=True)
    monkeypatch.delenv("DLAF_SECULAR_DEVICE_MIN_K")
    config.initialize()
    check(d, e, lam, q)
    np.testing.assert_allclose(lam, l_host, atol=1e-11)
