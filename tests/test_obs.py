"""Tests for the observability subsystem (dlaf_tpu.obs — ISSUE 1).

Covers: span nesting/reentrancy, counter/gauge/histogram semantics, the
JSONL schema round-trip (including NaN rejection — the CI gate's reason
to exist), the Prometheus exposition, DLAF_LOG level handling, the
zero-allocation no-op fast path when observability is off (acceptance
criterion), and the miniapp_cholesky integration: metrics enabled must
emit per-step records whose derived GFlop/s is finite, locally and —
with collective byte counters — on a 2x2 grid.
"""

import math
import os

import numpy as np
import pytest

import dlaf_tpu.config as C
from dlaf_tpu import obs


@pytest.fixture(autouse=True)
def obs_reset():
    """Leave every test with the suite's default unobserved config."""
    yield
    os.environ.pop("DLAF_METRICS_PATH", None)
    os.environ.pop("DLAF_TRACE_DIR", None)
    os.environ.pop("DLAF_LOG", None)
    obs._reset_for_tests()
    C.finalize()
    C.initialize()


def _configure_metrics(tmp_path, name="obs.jsonl"):
    path = str(tmp_path / name)
    C.initialize(C.Configuration(metrics_path=path))
    return path


# ---------------------------------------------------------------------------
# no-op fast path (acceptance criterion)
# ---------------------------------------------------------------------------

def test_noop_fast_path_when_disabled():
    """With observability unset every instrumented call site resolves to
    the same module-level no-op singleton — no per-call allocation."""
    C.initialize()   # defaults: no metrics path, no trace dir
    assert not obs.enabled()
    assert obs.span("a") is obs.NOOP_SPAN
    assert obs.span("b", flops=1.0, n=5) is obs.NOOP_SPAN
    assert obs.named_span("c") is obs.NOOP_CTX
    assert obs.counter("x", k="v") is obs.NOOP_COUNTER
    assert obs.gauge("y") is obs.NOOP_GAUGE
    assert obs.histogram("z") is obs.NOOP_HISTOGRAM
    # the singletons accept their whole API silently
    with obs.span("a") as sp:
        sp.set_attr("k", 1)
    obs.counter("x").inc(3)
    obs.gauge("y").set(2.0)
    obs.histogram("z").observe(0.1)
    # the comm instrumentation's gate
    assert not obs.metrics_active()
    # program telemetry off (the default): instrumented sites pass the
    # call straight to the SAME jitted callable — bitwise no-op (ISSUE 7
    # acceptance, pinned alongside the span/counter no-ops above)
    assert not obs.telemetry.active()
    sentinel = object()
    assert obs.telemetry.call("site", lambda x: x, sentinel) is sentinel
    obs.telemetry.count_retrace("site")       # silent no-op
    assert obs.telemetry._PROGRAMS == {}


def test_collectives_record_is_noop_when_disabled(devices8):
    """comm.collectives._record with metrics off touches no registry."""
    from dlaf_tpu.comm import collectives as cc

    C.initialize()
    cc._record("bcast", "row", np.zeros((4, 4)))
    assert not obs.metrics_active()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_span_nesting_and_reentrancy(tmp_path):
    path = _configure_metrics(tmp_path)
    with obs.span("outer", n=1):
        with obs.span("inner"):
            with obs.span("inner"):     # same name re-entered
                pass
    with obs.span("outer"):             # same name reused sequentially
        pass
    obs.flush()
    recs = [r for r in obs.read_records(path) if r["type"] == "span"]
    # spans emit on exit: innermost first
    names = [(r["name"], r["depth"], r["parent"]) for r in recs]
    assert names == [("inner", 2, "inner"), ("inner", 1, "outer"),
                     ("outer", 0, None), ("outer", 0, None)]
    for r in recs:
        assert r["dur_s"] >= 0 and math.isfinite(r["dur_s"])
    assert recs[2]["attrs"] == {"n": 1}


def test_span_gflops_derivation(tmp_path):
    path = _configure_metrics(tmp_path)
    with obs.span("work", flops=3e9):
        pass
    recs = [r for r in obs.read_records(path) if r["type"] == "span"]
    assert recs[0]["flops"] == 3e9
    assert math.isfinite(recs[0]["gflops"]) and recs[0]["gflops"] > 0
    # derived value consistent with the record's own duration
    assert recs[0]["gflops"] == pytest.approx(
        3e9 / recs[0]["dur_s"] / 1e9)


def test_entry_span_lazy_and_unfenced(tmp_path):
    """entry_span: attrs thunk never runs when off; when on, the record
    is marked unfenced and carries the flop model but no derived gflops
    (dispatch wall must not masquerade as throughput)."""
    C.initialize()
    calls = []
    assert obs.entry_span("algo", lambda: calls.append(1)) is obs.NOOP_SPAN
    assert calls == []

    path = _configure_metrics(tmp_path)
    with obs.entry_span("algo", lambda: dict(flops=1e9, n=64)):
        pass
    recs = [r for r in obs.read_records(path) if r["type"] == "span"]
    assert recs[0]["fenced"] is False
    assert recs[0]["flops"] == 1e9
    assert "gflops" not in recs[0]
    assert recs[0]["attrs"] == {"n": 64}
    # schema-valid, but does not satisfy the gflops requirement
    assert obs.validate_file(path, require_spans=True) == []
    assert obs.validate_file(path, require_gflops=True) != []


def test_bad_dlaf_log_env_is_lenient_on_lazy_path(monkeypatch, capsys):
    """A misspelled DLAF_LOG env must not crash informational log calls
    reached without config.initialize() (library use); it falls back to
    'info' with a note. The explicit initialize() path still raises."""
    obs._reset_for_tests()
    monkeypatch.setenv("DLAF_LOG", "warn")
    obs.get_logger("lenient").info("still works")
    err = capsys.readouterr().err
    assert "DLAF_LOG='warn'" in err and "using 'info'" in err
    assert "still works" in err
    with pytest.raises(ValueError):
        C.initialize()


def test_current_span_attrs(tmp_path):
    path = _configure_metrics(tmp_path)
    with obs.span("outer"):
        obs.current_span().set_attr("route", "mxu")
    recs = [r for r in obs.read_records(path) if r["type"] == "span"]
    assert recs[0]["attrs"] == {"route": "mxu"}


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_counter_semantics():
    reg = obs.Registry()
    c = reg.counter("hits", kind="bcast", axis="row")
    c.inc()
    c.inc(41)
    # same (name, labels) -> same accumulator; different labels -> distinct
    assert reg.counter("hits", kind="bcast", axis="row") is c
    other = reg.counter("hits", kind="bcast", axis="col")
    assert other is not c and other.value == 0.0
    snap = {(m["name"], tuple(sorted(m["labels"].items()))): m["value"]
            for m in reg.snapshot()}
    assert snap[("hits", (("axis", "row"), ("kind", "bcast")))] == 42.0


def test_gauge_and_histogram_semantics():
    reg = obs.Registry()
    g = reg.gauge("depth")
    g.set(3)
    g.set(7.5)
    assert reg.gauge("depth").value == 7.5

    h = reg.histogram("lat", bounds=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    s = h.snapshot()
    assert s["count"] == 5
    assert s["sum"] == pytest.approx(56.05)
    assert s["min"] == 0.05 and s["max"] == 50.0
    # cumulative Prometheus-style buckets, +Inf last
    assert s["buckets"] == [[0.1, 1], [1.0, 3], [10.0, 4], ["+Inf", 5]]


def test_prometheus_exposition():
    reg = obs.Registry()
    reg.counter("dlaf_comm_collective_bytes_total",
                kind="bcast", axis="row").inc(4096)
    reg.histogram("dlaf_span_seconds", bounds=(1.0,), span="x").observe(0.5)
    text = obs.prometheus_text(reg.snapshot())
    assert "# TYPE dlaf_comm_collective_bytes_total counter" in text
    assert ('dlaf_comm_collective_bytes_total{axis="row",kind="bcast"} '
            "4096.0") in text
    assert 'dlaf_span_seconds_bucket{le="1.0",span="x"} 1' in text
    assert 'dlaf_span_seconds_bucket{le="+Inf",span="x"} 1' in text
    assert 'dlaf_span_seconds_count{span="x"} 1' in text


def test_prometheus_histogram_inf_bucket_roundtrip():
    """The +Inf bucket renders as the literal ``le="+Inf"`` with the
    cumulative TOTAL count — including out-of-range observations that
    land in no finite bucket (the Prometheus invariant
    bucket{le="+Inf"} == count)."""
    reg = obs.Registry()
    h = reg.histogram("lat", bounds=(0.1, 1.0))
    for v in (0.05, 0.5, 100.0, 1e9):       # two past the last bound
        h.observe(v)
    text = obs.prometheus_text(reg.snapshot())
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1.0"} 2' in text
    assert 'lat_bucket{le="+Inf"} 4' in text
    assert "lat_count 4" in text
    # min/max survive the JSONL snapshot too
    s = h.snapshot()
    assert s["min"] == 0.05 and s["max"] == 1e9


def test_prometheus_label_escaping():
    """Backslash, double-quote, and newline in label values must escape
    per text exposition 0.0.4 — an unescaped newline would split the
    sample line and corrupt the whole scrape."""
    reg = obs.Registry()
    reg.counter("c", path='a\\b"c', msg="two\nlines").inc()
    text = obs.prometheus_text(reg.snapshot())
    assert '\\\\b' in text and '\\"c' in text
    assert "two\\nlines" in text
    assert "\ntwo" not in text            # no raw newline inside a value
    # exactly the TYPE line + one sample line
    assert len(text.strip().splitlines()) == 2


def test_prometheus_deterministic_ordering():
    """Exposition order is deterministic regardless of registration
    order: families sorted by (name, kind), series by sorted labels."""
    reg1, reg2 = obs.Registry(), obs.Registry()
    for reg, order in ((reg1, ("b", "a")), (reg2, ("a", "b"))):
        for axis in order:
            reg.counter("zz_total", axis=axis).inc()
        reg.gauge("aa_gauge").set(1)
    t1, t2 = obs.prometheus_text(reg1.snapshot()), \
        obs.prometheus_text(reg2.snapshot())
    assert t1 == t2
    assert t1.index("aa_gauge") < t1.index("zz_total")
    assert t1.index('axis="a"') < t1.index('axis="b"')


# ---------------------------------------------------------------------------
# JSONL schema round-trip + validation
# ---------------------------------------------------------------------------

def test_jsonl_schema_roundtrip(tmp_path):
    path = _configure_metrics(tmp_path)
    with obs.span("region", flops=1e9, n=64):
        obs.counter("dlaf_comm_collective_bytes_total",
                    kind="bcast", axis="row").inc(1 << 20)
    obs.get_logger("test").warning("note", key="val")
    obs.emit_event("bench_result", payload={"gflops": 1.5})
    obs.flush()
    errs = obs.validate_file(path, require_spans=True, require_gflops=True,
                             require_collectives=True)
    assert errs == []
    by_type = {}
    for r in obs.read_records(path):
        by_type.setdefault(r["type"], []).append(r)
        assert r["v"] == obs.SCHEMA_VERSION
        assert math.isfinite(r["ts"])
    assert set(by_type) == {"span", "log", "bench_result", "metrics"}
    assert by_type["bench_result"][0]["payload"] == {"gflops": 1.5}
    assert by_type["log"][0]["msg"] == "note"
    assert by_type["log"][0]["fields"] == {"key": "val"}


def test_validator_rejects_nan_and_missing_fields(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    sink = obs.JsonlSink(path)
    sink.write({"type": "span", "name": "x", "dur_s": float("nan"),
                "depth": 0, "parent": None, "attrs": {}})
    sink.write({"type": "span", "dur_s": 0.5, "depth": 0, "parent": None,
                "attrs": {}})                     # missing name
    sink.write({"type": "span", "name": "ok", "dur_s": 0.1, "depth": 0,
                "parent": None, "attrs": {}, "gflops": float("inf")})
    sink.write({"type": "mystery"})               # unknown type
    sink.write({"type": "span", "name": "d", "dur_s": 0.1, "depth": 0,
                "parent": None, "attrs": {}, "fenced": False,
                "gflops": 99999.0})   # dispatch wall masquerading as rate
    sink.close()
    errs = obs.validate_file(path)
    assert len(errs) == 5
    assert any("dur_s" in e for e in errs)
    assert any("without a name" in e for e in errs)
    assert any("gflops non-finite" in e for e in errs)
    assert any("unknown type" in e for e in errs)
    assert any("unfenced span must not carry gflops" in e for e in errs)


def test_validator_requires_content(tmp_path):
    path = str(tmp_path / "empty.jsonl")
    open(path, "w").close()
    errs = obs.validate_file(path, require_spans=True, require_gflops=True,
                             require_collectives=True)
    assert len(errs) == 3


def test_validator_requires_comm_overlap(tmp_path):
    """--require-comm-overlap: positive finite overlap counters AND finite
    per-axis byte counters for BOTH mesh axes (docs/comm_overlap.md);
    non-finite or single-axis artifacts fail."""
    def write(path, metrics):
        sink = obs.JsonlSink(str(path))
        sink.write({"type": "metrics", "metrics": metrics})
        sink.close()
        return str(path)

    def counter(name, value, **labels):
        return {"name": name, "kind": "counter", "value": value,
                "labels": labels}

    good = write(tmp_path / "good.jsonl", [
        counter("dlaf_comm_overlapped_total", 4, algo="cholesky_dist",
                axis="row"),
        counter("dlaf_comm_overlapped_total", 4, algo="cholesky_dist",
                axis="col"),
        counter("dlaf_comm_collective_bytes_total", 128, kind="bcast2d",
                axis="row"),
        counter("dlaf_comm_collective_bytes_total", 128, kind="bcast2d",
                axis="col"),
    ])
    assert obs.validate_file(good, require_comm_overlap=True) == []
    # one axis missing -> both obligations can fail independently
    partial = write(tmp_path / "partial.jsonl", [
        counter("dlaf_comm_overlapped_total", 4, algo="cholesky_dist",
                axis="row"),
        counter("dlaf_comm_collective_bytes_total", 128, kind="bcast",
                axis="row"),
    ])
    errs = obs.validate_file(partial, require_comm_overlap=True)
    assert any("dlaf_comm_overlapped_total" in e for e in errs)
    assert any("dlaf_comm_collective_bytes_total" in e for e in errs)
    # non-finite counter values (NaN AND +inf) must not satisfy the
    # requirement — the shared _finite gate filters both before the
    # axis sets are populated
    for bad in (float("nan"), float("inf")):
        art = write(tmp_path / f"bad_{bad}.jsonl", [
            counter("dlaf_comm_overlapped_total", bad,
                    algo="cholesky_dist", axis="row"),
            counter("dlaf_comm_overlapped_total", 4, algo="cholesky_dist",
                    axis="col"),
        ])
        errs = obs.validate_file(art, require_comm_overlap=True)
        assert any("dlaf_comm_overlapped_total" in e for e in errs), bad


def test_validate_cli(tmp_path, capsys):
    from dlaf_tpu.obs.validate import main

    path = _configure_metrics(tmp_path)
    with obs.span("r", flops=1e6):
        pass
    obs.flush()
    assert main([path, "--require-spans", "--require-gflops"]) == 0
    assert main([path, "--require-collectives"]) == 1
    assert main(["--nonsense", path]) == 2
    capsys.readouterr()


def test_validate_cli_exit_codes(tmp_path, capsys):
    """The pinned CLI contract (ISSUE 7 satellite): 2 on unknown flag or
    no/multiple paths; 1 on an empty artifact under ANY --require-*."""
    from dlaf_tpu.obs.validate import main

    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert main([]) == 2                               # no path
    assert main([empty, empty]) == 2                   # two paths
    assert main([empty, "--require-thing"]) == 2       # unknown flag
    assert main([empty, "--history", "--require-spans"]) == 2  # exclusive
    assert main([empty]) == 0                          # empty, no require
    for flag in ("--require-spans", "--require-gflops",
                 "--require-collectives", "--require-retries",
                 "--require-fallbacks", "--require-comm-overlap",
                 "--require-dc-batch", "--require-bt-overlap",
                 "--require-telemetry"):
        assert main([empty, flag]) == 1, flag
    capsys.readouterr()


def test_validator_rank_field(tmp_path):
    """Optional ``rank`` must be a non-negative int when present."""
    path = str(tmp_path / "rank.jsonl")
    sink = obs.JsonlSink(path)
    sink.write({"type": "span", "name": "x", "dur_s": 0.1, "depth": 0,
                "parent": None, "attrs": {}, "rank": 3})
    sink.write({"type": "span", "name": "y", "dur_s": 0.1, "depth": 0,
                "parent": None, "attrs": {}, "rank": -1})
    sink.write({"type": "span", "name": "z", "dur_s": 0.1, "depth": 0,
                "parent": None, "attrs": {}, "rank": "r0"})
    sink.close()
    errs = obs.validate_file(path)
    assert len(errs) == 2 and all("rank" in e for e in errs)


def test_validator_program_records(tmp_path):
    """The telemetry record type: compile events need a finite
    compile_s; hbm values must all be finite."""
    path = str(tmp_path / "prog.jsonl")
    sink = obs.JsonlSink(path)
    sink.write({"type": "program", "site": "cholesky.dist",
                "event": "compile", "compile_s": 0.5, "trace_s": 0.1,
                "hbm": {"args": 1.0, "peak": 2.0}, "attrs": {}})
    sink.write({"type": "program", "site": "cholesky.dist",
                "event": "retrace", "attrs": {}})
    sink.close()
    assert obs.validate_file(path) == []

    bad = str(tmp_path / "prog_bad.jsonl")
    sink = obs.JsonlSink(bad)
    sink.write({"type": "program", "event": "compile",
                "compile_s": 0.5, "attrs": {}})              # no site
    sink.write({"type": "program", "site": "s", "event": "compile",
                "compile_s": float("nan"), "attrs": {}})     # NaN wall
    sink.write({"type": "program", "site": "s", "event": "compile",
                "compile_s": 0.1, "hbm": {"peak": float("inf")},
                "attrs": {}})                                # inf HBM
    sink.write({"type": "program", "site": "s", "event": "link",
                "attrs": {}})                                # bad event
    sink.write({"type": "program", "site": "s", "event": "retrace",
                "compile_s": float("nan"), "attrs": {}})     # NaN anywhere
    sink.close()
    errs = obs.validate_file(bad)
    assert len(errs) == 5
    assert any("without a site" in e for e in errs)
    assert any("compile_s" in e for e in errs)
    assert any("hbm['peak']" in e for e in errs)
    assert any("compile|retrace" in e for e in errs)


def test_validator_require_telemetry(tmp_path):
    """--require-telemetry: compile observation + HBM accounting +
    retrace evidence must ALL be present; each missing leg fails
    independently, and each leg accepts either the metrics-snapshot or
    the program-record form."""
    path = str(tmp_path / "tele.jsonl")
    sink = obs.JsonlSink(path)
    sink.write({"type": "program", "site": "s", "event": "compile",
                "compile_s": 0.2, "attrs": {}})
    sink.write({"type": "metrics", "metrics": [
        {"name": "dlaf_hbm_bytes", "kind": "gauge",
         "labels": {"what": "peak", "site": "s"}, "value": 1024.0},
        {"name": "dlaf_retrace_total", "kind": "counter",
         "labels": {"site": "s"}, "value": 1.0}]})
    sink.close()
    assert obs.validate_file(path, require_telemetry=True) == []

    # program records ALONE satisfy all three legs: a run killed before
    # the final metrics snapshot still validates on its record trail
    recs_only = str(tmp_path / "tele_recs.jsonl")
    sink = obs.JsonlSink(recs_only)
    sink.write({"type": "program", "site": "s", "event": "retrace",
                "attrs": {}})
    sink.write({"type": "program", "site": "s", "event": "compile",
                "compile_s": 0.2, "hbm": {"peak": 1024.0}, "attrs": {}})
    sink.close()
    assert obs.validate_file(recs_only, require_telemetry=True) == []

    partial = str(tmp_path / "tele_partial.jsonl")
    sink = obs.JsonlSink(partial)
    sink.write({"type": "log", "level": "info", "logger": "t", "msg": "m",
                "fields": {}})
    sink.close()
    errs = obs.validate_file(partial, require_telemetry=True)
    assert len(errs) == 3
    assert any("compile-seconds" in e for e in errs)
    assert any("HBM accounting" in e for e in errs)
    assert any("retrace evidence" in e for e in errs)
    # one leg present, two missing: fails on exactly the missing two
    compile_only = str(tmp_path / "tele_compile_only.jsonl")
    sink = obs.JsonlSink(compile_only)
    sink.write({"type": "program", "site": "s", "event": "compile",
                "compile_s": 0.2, "attrs": {}})
    sink.close()
    errs = obs.validate_file(compile_only, require_telemetry=True)
    assert len(errs) == 2
    assert any("HBM accounting" in e for e in errs)
    assert any("retrace evidence" in e for e in errs)


# ---------------------------------------------------------------------------
# logging / DLAF_LOG
# ---------------------------------------------------------------------------

def test_log_levels(capsys):
    C.initialize(C.Configuration(log="warning"))
    lg = obs.get_logger("lvl")
    lg.info("hidden")
    lg.warning("shown", a=1)
    err = capsys.readouterr().err
    assert "hidden" not in err
    assert "dlaf_tpu[warning] lvl: shown [a=1]" in err

    C.initialize(C.Configuration(log="off"))
    lg.error("silent")
    assert capsys.readouterr().err == ""


def test_log_env_layering(monkeypatch):
    monkeypatch.setenv("DLAF_LOG", "error")
    cfg = C.update_configuration(C.Configuration(log="debug"))
    assert cfg.log == "error"            # env over user struct
    cfg = C.update_configuration(argv=["--dlaf:log=off"])
    assert cfg.log == "off"              # CLI over env
    monkeypatch.delenv("DLAF_LOG")
    with pytest.raises(ValueError):
        C.initialize(C.Configuration(log="loud"))


def test_warning_once(capsys):
    C.initialize()
    lg = obs.get_logger("once")
    lg.warning_once("k1", "first")
    lg.warning_once("k1", "first")
    lg.warning_once("k2", "second")
    err = capsys.readouterr().err
    assert err.count("first") == 1 and err.count("second") == 1


def test_warning_once_not_consumed_while_suppressed(capsys):
    """A suppressed one-shot key stays unconsumed: raising the log level
    later must still produce the single announcement (a process that
    starts with DLAF_LOG=error would otherwise permanently lose the
    auto-knob resolution notices)."""
    C.initialize(C.Configuration(log="error"))
    lg = obs.get_logger("once_lvl")
    lg.warning_once("k", "notice")
    assert capsys.readouterr().err == ""
    C.initialize(C.Configuration(log="info"))
    lg.warning_once("k", "notice")
    lg.warning_once("k", "notice")
    assert capsys.readouterr().err.count("notice") == 1


def test_resolution_notices_respect_dlaf_log(capsys):
    """The auto-knob notices (satellite: config.py print -> logger) are
    silenceable — DLAF_LOG=off in CI/pytest output."""
    C.initialize(C.Configuration(log="off"))
    key = ("t_obs_knob", "cpu", "native")
    from dlaf_tpu.obs.logging import forget_once

    forget_once("config", key)
    try:
        out = C.resolve_platform_auto("auto", knob="t_obs_knob",
                                      tpu_choice="mxu",
                                      other_choice="native", detail="d")
        assert out == "native"
        assert capsys.readouterr().err == ""
    finally:
        forget_once("config", key)


# ---------------------------------------------------------------------------
# PhaseTimer migration
# ---------------------------------------------------------------------------

def test_phase_timer_emits_spans(tmp_path):
    from dlaf_tpu.common.timer import PhaseTimer

    path = _configure_metrics(tmp_path)
    pt = PhaseTimer()
    with pt.phase("stage_a"):
        pass
    with pt.phase("stage_a"):
        pass
    assert set(pt.report()) == {"stage_a"}
    names = [r["name"] for r in obs.read_records(path)
             if r["type"] == "span"]
    assert names == ["stage_a", "stage_a"]


def test_phase_timer_profiler_single_owner(tmp_path, monkeypatch):
    """A timer-owned jax.profiler trace claims the obs layer's
    profiler_started flag, so a configure(trace_dir=...) landing mid-phase
    (lazy config init inside an algorithm call) cannot start_trace a
    second time over the live trace."""
    import jax

    from dlaf_tpu.common.timer import PhaseTimer
    from dlaf_tpu.obs._state import STATE

    calls = {"start": 0, "stop": 0}
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda *a, **k: calls.__setitem__(
                            "start", calls["start"] + 1))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.__setitem__("stop", calls["stop"] + 1))

    pt = PhaseTimer(profile_dir=str(tmp_path / "timer_trace"))
    with pt.phase("stage_a"):
        # mid-phase: the obs layer comes up with its own trace dir and a
        # span triggers its lazy profiler start — must see the claim
        C.initialize(C.Configuration(trace_dir=str(tmp_path / "obs_trace")))
        with obs.span("inner"):
            pass
    assert calls["start"] == 1 and STATE.profiler_started
    pt.stop()
    assert calls["stop"] == 1 and not STATE.profiler_started


def test_stopped_profiler_does_not_restart(tmp_path, monkeypatch):
    """Once the process trace is stopped, later spans must not silently
    start a new one into the stale directory — in a long-lived process
    (pytest was the victim) that trace would record everything until
    interpreter exit."""
    import jax

    calls = {"start": 0, "stop": 0}
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda *a, **k: calls.__setitem__(
                            "start", calls["start"] + 1))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.__setitem__("stop", calls["stop"] + 1))

    C.initialize(C.Configuration(trace_dir=str(tmp_path / "t")))
    with obs.span("a"):
        pass
    assert calls["start"] == 1
    obs.stop_profiler()
    assert calls["stop"] == 1
    with obs.span("b"):
        pass
    assert calls["start"] == 1, "span restarted a stopped process trace"


def test_pipeline_phase_names_avoid_entry_span_collision():
    """Pipeline stage spans must not reuse algorithm entry-span names: a
    fenced stage wall-time span sharing a name with an unfenced
    dispatch-time entry span would merge two different populations into
    one dlaf_span_seconds histogram."""
    import importlib
    import inspect
    import re

    # importlib: the packages re-export same-named functions that shadow
    # the submodule attribute on plain ``import a.b.c as c``
    es = importlib.import_module("dlaf_tpu.eigensolver.eigensolver")
    mods = [es] + [importlib.import_module(m) for m in (
        "dlaf_tpu.algorithms.cholesky",
        "dlaf_tpu.algorithms.gen_to_std",
        "dlaf_tpu.algorithms.triangular",
        "dlaf_tpu.eigensolver.reduction_to_band",
    )]
    phases = set(re.findall(r'\.phase\(\s*"([^"]+)"',
                            inspect.getsource(es)))
    entries = set()
    for mod in mods:
        entries |= set(re.findall(r'entry_span\(\s*"([^"]+)"',
                                  inspect.getsource(mod)))
    assert phases and entries
    assert phases.isdisjoint(entries), phases & entries


# ---------------------------------------------------------------------------
# miniapp integration (acceptance criterion)
# ---------------------------------------------------------------------------

def _run_miniapp_with_metrics(tmp_path, monkeypatch, extra_args=()):
    from dlaf_tpu.miniapp.miniapp_cholesky import run as crun

    path = str(tmp_path / "mc.jsonl")
    monkeypatch.setenv("DLAF_METRICS_PATH", path)
    out = crun(["-m", "128", "-b", "32", "--nruns", "2", *extra_args])
    assert len(out) == 2
    return path


def test_miniapp_cholesky_metrics_integration(tmp_path, monkeypatch):
    """miniapp_cholesky with metrics enabled emits per-step records whose
    derived GFlop/s is finite, and the artifact is schema-valid."""
    path = _run_miniapp_with_metrics(tmp_path, monkeypatch)
    assert obs.validate_file(path, require_spans=True,
                             require_gflops=True) == []
    runs = [r for r in obs.read_records(path)
            if r["type"] == "span" and r["name"] == "miniapp_cholesky.run"]
    timed = [r for r in runs if not r["attrs"]["warmup"]]
    assert len(timed) == 2                      # one record per timed step
    for r in runs:
        assert math.isfinite(r["gflops"]) and r["gflops"] > 0
        assert r["attrs"]["n"] == 128 and r["attrs"]["nb"] == 32


def test_miniapp_cholesky_metrics_distributed(tmp_path, monkeypatch,
                                              devices8):
    """The 2x2-grid artifact additionally carries positive per-axis
    collective byte counters (the CI smoke gate's contract)."""
    path = _run_miniapp_with_metrics(
        tmp_path, monkeypatch, ("--grid-rows", "2", "--grid-cols", "2"))
    assert obs.validate_file(path, require_spans=True, require_gflops=True,
                             require_collectives=True) == []
    snaps = [r for r in obs.read_records(path) if r["type"] == "metrics"]
    bytes_by_axis = {}
    for m in snaps[-1]["metrics"]:
        if m["name"] == "dlaf_comm_collective_bytes_total":
            bytes_by_axis[m["labels"]["axis"]] = \
                bytes_by_axis.get(m["labels"]["axis"], 0) + m["value"]
    assert bytes_by_axis.get("row", 0) > 0
    assert bytes_by_axis.get("col", 0) > 0
