"""Tests for the emulated-f64 MXU gemm (tile_ops.ozaki) and the
mixed-precision panel helpers (tile_ops.mixed), plus the cholesky_trailing
="ozaki" fast path end to end.

Verification style follows the reference's analytic approach
(``test/unit/test_blas_tile``): known inputs, error budgets scaled to the
operand magnitudes.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dlaf_tpu.tile_ops.ozaki import matmul_f64, syrk_f64
from dlaf_tpu.tile_ops.mixed import potrf_refined, tri_inv_refined

EPS = np.finfo(np.float64).eps


def _scaled_err(got, ref, a, b):
    scale = (np.abs(a).max(axis=-1)[..., :, None]
             * np.abs(b).max(axis=-2)[..., None, :] * a.shape[-1])
    return (np.abs(got - ref) / np.maximum(scale, 1e-300)).max()


class TestOzakiMatmul:
    def test_accuracy_f64_grade(self):
        rng = np.random.default_rng(7)
        a = rng.standard_normal((96, 200))
        b = rng.standard_normal((200, 64))
        got = np.asarray(matmul_f64(a, b))
        assert _scaled_err(got, a @ b, a, b) < 4 * EPS

    @pytest.mark.parametrize("m,k,n", [(32, 64, 16), (8, 16, 8), (1, 4, 1),
                                       (100, 7, 33)])
    def test_pathological_row_col_scales(self, m, k, n):
        # full f64 exponent range is a CPU-path guarantee; on TPU the X64
        # emulation (f32 pairs) caps all f64 magnitudes at ~1e38 (see
        # module docstring) — tests run on CPU
        rng = np.random.default_rng(8)
        a = rng.standard_normal((m, k))
        b = rng.standard_normal((k, n))
        a[0] *= 2.0**180
        a[-1] *= 2.0**-170
        b[:, 0] *= 2.0**120
        got = np.asarray(matmul_f64(a, b))
        assert _scaled_err(got, a @ b, a, b) < 4 * EPS

    def test_near_dbl_max_rows_stay_finite(self):
        # scale handling must not overflow on its own: finite inputs with
        # near-DBL_MAX magnitudes give finite, correct results as long as
        # the true product is representable
        a = np.full((4, 4), 1e308)
        got = np.asarray(matmul_f64(a, np.eye(4)))
        assert np.isfinite(got).all()
        np.testing.assert_allclose(got, a, rtol=1e-15)

    def test_zero_rows_and_batch(self):
        rng = np.random.default_rng(9)
        a = rng.standard_normal((2, 3, 24, 40))
        b = rng.standard_normal((2, 3, 40, 8))
        a[..., 0, :] = 0.0
        got = np.asarray(matmul_f64(a, b))
        assert np.isfinite(got).all()
        assert _scaled_err(got, a @ b, a, b) < 4 * EPS

    def test_fewer_slices_tracks_bound(self):
        rng = np.random.default_rng(10)
        a = rng.standard_normal((48, 48))
        b = rng.standard_normal((48, 48))
        err6 = np.abs(np.asarray(matmul_f64(a, b, slices=6)) - a @ b).max()
        err8 = np.abs(np.asarray(matmul_f64(a, b, slices=8)) - a @ b).max()
        assert err8 < err6          # more slices -> strictly more mantissa
        assert err6 < 48 * 2.0**-40  # ~2^-42 relative to ~unit row scales

    def test_deep_contraction_chunks_exactly(self):
        # k * 2^12 == 2^31 at k = 2^19: a single int32 dot accumulation
        # would wrap (round-1 advisor finding — reachable via blas.contract
        # flattening several contracted dims); the chunked _dot_i8 path
        # must stay exact
        k = 1 << 19
        a = np.ones((1, k))
        b = np.ones((k, 1))
        got = np.asarray(matmul_f64(a, b))
        np.testing.assert_allclose(got, [[float(k)]], rtol=1e-15)

    def test_syrk_matches_matmul(self):
        rng = np.random.default_rng(11)
        a = rng.standard_normal((56, 72))
        got = np.asarray(syrk_f64(a))
        assert _scaled_err(got, a @ a.T, a, np.swapaxes(a, -1, -2)) < 4 * EPS
        assert np.allclose(got, got.T)  # symmetry by construction


class TestPallasFused:
    """ozaki_impl="pallas": the fused per-tile slice reduction (interpret
    mode on CPU) must agree with the jnp path to the double-f32 fold's
    documented accuracy (~2^-48 relative to row/col scales)."""

    def _knob(self, monkeypatch):
        monkeypatch.setenv("DLAF_OZAKI_IMPL", "pallas")
        import dlaf_tpu.config as config
        config.initialize()
        return config

    def test_matmul_and_syrk_match(self, monkeypatch):
        config = self._knob(monkeypatch)
        try:
            rng = np.random.default_rng(21)
            a = rng.standard_normal((100, 200))
            b = rng.standard_normal((200, 70))
            a[0] *= 2.0**120
            b[:, 3] *= 2.0**-90
            got = np.asarray(matmul_f64(a, b))
            assert _scaled_err(got, a @ b, a, b) < 16 * EPS
            gs = np.asarray(syrk_f64(a))
            assert _scaled_err(gs, a @ a.T, a, np.swapaxes(a, -1, -2)) < 16 * EPS
        finally:
            monkeypatch.delenv("DLAF_OZAKI_IMPL")
            config.initialize()

    @pytest.mark.parametrize("m,k", [(100, 200), (513, 64)])
    def test_syrk_triangular_grid(self, m, k, monkeypatch):
        """The symmetric kernel computes only lower-triangle tiles (scalar-
        prefetched pair index); the mirrored result must match numpy at
        ragged sizes (padding + edge tiles)."""
        config = self._knob(monkeypatch)
        try:
            rng = np.random.default_rng(m)
            a = rng.standard_normal((m, k))
            a[0] *= 2.0**90
            got = np.asarray(syrk_f64(a))
            ss = np.abs(a).max(1)[:, None] * np.abs(a).max(1)[None, :] * k
            assert (np.abs(got - a @ a.T) / ss).max() < 16 * EPS
        finally:
            monkeypatch.delenv("DLAF_OZAKI_IMPL")
            config.initialize()

    def test_masked_slice_product_predication(self):
        """The predicated kernel must equal the plain product on live tile
        pairs and produce exact zeros on dead ones."""
        from dlaf_tpu.tile_ops import ozaki as oz
        from dlaf_tpu.tile_ops.pallas_ozaki import masked_slice_product

        rng = np.random.default_rng(31)
        R, C, mb = 3, 2, 16
        s = 8
        a = rng.standard_normal((R * mb, mb))
        b = rng.standard_normal((C * mb, mb))
        sa = np.asarray(oz._scale(jnp.asarray(a), axis=-1))
        sb = np.asarray(oz._scale(jnp.asarray(b), axis=-1))
        ia = jnp.stack(oz._peel_slices(jnp.asarray(a / sa * 0.5), s))
        ib = jnp.stack(oz._peel_slices(jnp.asarray(b / sb * 0.5), s))
        mode = np.array([[1, 0], [0, 1], [1, 1]], dtype=np.int32)
        hi, lo = masked_slice_product(ia.reshape(s, R, mb, mb),
                                      ib.reshape(s, C, mb, mb),
                                      jnp.asarray(mode), interpret=True)
        acc = (np.asarray(hi, np.float64) + np.asarray(lo, np.float64)) * 4.0
        acc = acc * sa.reshape(R, 1, mb, 1) * sb.reshape(1, C, 1, mb)
        full = a @ b.T
        for r in range(R):
            for c in range(C):
                blk = full[r * mb:(r + 1) * mb, c * mb:(c + 1) * mb]
                if mode[r, c]:
                    scale = (np.abs(a).max() * np.abs(b).max() * mb)
                    assert np.abs(acc[r, c] - blk).max() / scale < 2**-40
                else:
                    assert np.all(acc[r, c] == 0.0)

    def test_dist_cholesky_exact_flop_oz_pallas(self, monkeypatch, devices8):
        """f64_gemm="mxu" + ozaki_impl="pallas" distributed: the predicated
        trailing kernel (dead tile pairs skipped) must reproduce the plain
        mxu path's factorization."""
        monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
        monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "8")
        import dlaf_tpu.config as config
        config.initialize()
        try:
            from dlaf_tpu.algorithms.cholesky import cholesky
            from dlaf_tpu.comm.grid import Grid
            from dlaf_tpu.common.index2d import (GlobalElementSize,
                                                 TileElementSize)
            from dlaf_tpu.matrix.matrix import Matrix
            from dlaf_tpu.miniapp.generators import hpd_element_fn

            n, nb = 64, 8
            mat = Matrix.from_element_fn(
                hpd_element_fn(n, np.float64), GlobalElementSize(n, n),
                TileElementSize(nb, nb), dtype=np.float64, grid=Grid(2, 4))
            a = mat.to_numpy()
            for uplo in ("L", "U"):
                monkeypatch.setenv("DLAF_OZAKI_IMPL", "pallas")
                config.initialize()
                got = cholesky(uplo, mat).to_numpy()
                monkeypatch.setenv("DLAF_OZAKI_IMPL", "jnp")
                config.initialize()
                ref = cholesky(uplo, mat).to_numpy()
                tri = np.tril if uplo == "L" else np.triu
                f = tri(got)
                resid = (np.linalg.norm(f @ f.T - a) if uplo == "L"
                         else np.linalg.norm(f.T @ f - a)) / np.linalg.norm(a)
                assert resid < 60 * n * EPS, (uplo, resid)
                assert np.abs(tri(got) - tri(ref)).max() < 1e-10
        finally:
            monkeypatch.delenv("DLAF_F64_GEMM")
            monkeypatch.delenv("DLAF_F64_GEMM_MIN_DIM")
            monkeypatch.delenv("DLAF_OZAKI_IMPL", raising=False)
            config.initialize()

    def test_cholesky_ozaki_under_pallas_impl(self, monkeypatch):
        monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "ozaki")
        config = self._knob(monkeypatch)
        try:
            from dlaf_tpu.algorithms.cholesky import cholesky
            from dlaf_tpu.common.index2d import (GlobalElementSize,
                                                 TileElementSize)
            from dlaf_tpu.matrix.matrix import Matrix
            from dlaf_tpu.miniapp.generators import hpd_element_fn

            n, nb = 256, 64
            mat = Matrix.from_element_fn(
                hpd_element_fn(n, np.float64), GlobalElementSize(n, n),
                TileElementSize(nb, nb), dtype=np.float64)
            out = cholesky("L", mat)
            f = np.tril(out.to_numpy())
            resid = np.linalg.norm(f @ f.T - mat.to_numpy()) \
                / np.linalg.norm(mat.to_numpy())
            assert resid < 60 * n * EPS
        finally:
            monkeypatch.delenv("DLAF_OZAKI_IMPL")
            monkeypatch.delenv("DLAF_CHOLESKY_TRAILING")
            config.initialize()


class TestFusedKernelExactness:
    """The BASELINE.md round-2 pending interpret-mode parity pins
    (ISSUE 15 satellite): the rewritten predicated-square-grid fused
    slice kernels' numerical contract checked EXACTLY, not just within
    tolerance — the per-shift int32 group sums are exact integers, and
    the double-f32 fold is a deterministic f32 op sequence, so the
    kernels can be pinned against an independent numpy replay of that
    sequence bit for bit. (Hardware re-probe stays pending on the
    tunnel, docs/ROUND4.md; these pins make a future silicon run a
    drop-in check instead of a debug session.)"""

    S = 6

    def _slices(self, m, k, n, seed=41):
        from dlaf_tpu.tile_ops import ozaki as oz

        rng = np.random.default_rng(seed)
        a = rng.standard_normal((m, k))
        b = rng.standard_normal((k, n))
        sa = np.asarray(oz._scale(jnp.asarray(a), axis=-1))
        sb = np.asarray(oz._scale(jnp.asarray(b), axis=-2))
        ia = jnp.stack(oz._peel_slices(jnp.asarray(a / sa * 0.5), self.S))
        ib = jnp.stack(oz._peel_slices(jnp.asarray(b / sb * 0.5), self.S))
        return ia, ib

    @staticmethod
    def _fold_reference(ia, ib):
        """Numpy replay of pallas_ozaki._fold_body: exact int64 group
        sums, the exact int32 -> double-f32 split, and the two-sum fold
        — the kernels must reproduce this BIT FOR BIT."""
        from dlaf_tpu.tile_ops.ozaki import SLICE_BITS

        s = ia.shape[0]
        ia64 = np.asarray(ia, np.int64)
        ib64 = np.asarray(ib, np.int64)
        hi = np.zeros((ia.shape[1], ib.shape[2]), np.float32)
        lo = np.zeros_like(hi)
        for d in range(s):
            p = np.zeros_like(hi, dtype=np.int64)
            for t in range(d + 1):
                p = p + ia64[t] @ ib64[d - t]
            phi = p.astype(np.float32)
            plo = (p - phi.astype(np.int64)).astype(np.float32)
            scale = np.float32(2.0 ** (-SLICE_BITS * (d + 2)))
            # Knuth two-sum in f32, exactly as the kernel spells it
            b32 = phi * scale
            ssum = hi + b32
            bb = ssum - hi
            err = (hi - (ssum - bb)) + (b32 - bb)
            hi = ssum
            lo = lo + (err + plo * scale)
        return hi, lo

    def test_fused_product_matches_exact_fold_replay(self):
        from dlaf_tpu.tile_ops.pallas_ozaki import fused_slice_product

        ia, ib = self._slices(40, 64, 24)
        hi, lo = fused_slice_product(ia, ib, block_m=16, block_n=16,
                                     interpret=True)
        rhi, rlo = self._fold_reference(ia, ib)
        assert np.array_equal(np.asarray(hi), rhi)
        assert np.array_equal(np.asarray(lo), rlo)

    def test_fused_dot_routes_bit_identical(self):
        """int8 vs bf16 slice dots (the ozaki_dot A/B, integer-exact by
        the k*2^12 <= 2^24 bound): identical hi AND lo planes."""
        from dlaf_tpu.tile_ops.pallas_ozaki import fused_slice_product

        ia, ib = self._slices(32, 48, 32)
        h8, l8 = fused_slice_product(ia, ib, block_m=16, block_n=16,
                                     interpret=True, dot="int8")
        hb, lb = fused_slice_product(ia, ib, block_m=16, block_n=16,
                                     interpret=True, dot="bf16")
        assert np.array_equal(np.asarray(h8), np.asarray(hb))
        assert np.array_equal(np.asarray(l8), np.asarray(lb))

    def test_fused_syrk_matches_product_on_lower_tiles(self):
        """The predicated syrk (strict-upper tiles skipped) equals the
        general product of the same slices on every lower tile, bit for
        bit, and is exactly zero above the block diagonal."""
        from dlaf_tpu.tile_ops.pallas_ozaki import (fused_slice_product,
                                                    fused_slice_syrk)

        ia, _ = self._slices(48, 32, 8)
        block = 16
        hs, ls = fused_slice_syrk(ia, block=block, interpret=True)
        hp, lp = fused_slice_product(ia, jnp.swapaxes(ia, 1, 2),
                                     block_m=block, block_n=block,
                                     interpret=True)
        m = ia.shape[1]
        nt = m // block
        for r in range(nt):
            for c in range(nt):
                sl = (slice(r * block, (r + 1) * block),
                      slice(c * block, (c + 1) * block))
                if c <= r:
                    assert np.array_equal(np.asarray(hs[sl]),
                                          np.asarray(hp[sl])), (r, c)
                    assert np.array_equal(np.asarray(ls[sl]),
                                          np.asarray(lp[sl])), (r, c)
                else:
                    assert np.all(np.asarray(hs[sl]) == 0.0)
                    assert np.all(np.asarray(ls[sl]) == 0.0)


class TestContract:
    """blas.contract: the einsum->slice-product factorization must equal
    jnp.einsum for every pattern the algorithms use, real and complex."""

    PATTERNS = [
        ("rab,cbd->rcad", (3, 4, 5), (2, 5, 6)),    # triangular/bt trailing
        ("rcab,cbd->rad", (3, 2, 4, 5), (2, 5, 6)),  # red2band W partial
        ("rab,rad->bd", (3, 4, 5), (3, 4, 6)),       # red2band M partial
        ("rad,cbd->rcab", (3, 4, 6), (2, 5, 6)),     # red2band her2k-like
        ("tb,tbm->tm", (4, 5), (4, 5, 6)),           # bt sweeps (batched)
        ("rab,rcad->cbd", (3, 4, 5), (3, 2, 4, 6)),  # bt_b2t W2 partial
        ("xb,cbd->cxd", (4, 5), (2, 5, 6)),          # bt_b2t T apply
    ]

    @pytest.mark.parametrize("sub,shx,shy", PATTERNS)
    @pytest.mark.parametrize("cplx", [False, True])
    def test_matches_einsum_on_mxu_path(self, sub, shx, shy, cplx,
                                        monkeypatch):
        monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
        monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "2")
        import dlaf_tpu.config as config
        config.initialize()
        try:
            from dlaf_tpu.tile_ops.blas import contract
            rng = np.random.default_rng(hash(sub) % 2**31)
            x = rng.standard_normal(shx)
            y = rng.standard_normal(shy)
            if cplx:
                x = x + 1j * rng.standard_normal(shx)
                y = y + 1j * rng.standard_normal(shy)
            got = np.asarray(contract(sub, x, y))
            np.testing.assert_allclose(got, np.einsum(sub, x, y),
                                       rtol=1e-12, atol=1e-12)
        finally:
            monkeypatch.delenv("DLAF_F64_GEMM")
            monkeypatch.delenv("DLAF_F64_GEMM_MIN_DIM")
            config.initialize()

    @pytest.mark.parametrize("which", ["x", "y"])
    def test_mixed_real_complex_native_fallback(self, which):
        # native (non-mxu) branch with one real and one complex operand:
        # preferred_element_type must follow result_type, not x.dtype
        # (round-1 advisor finding — f64 preferred type on a complex
        # contraction is invalid/lossy)
        from dlaf_tpu.tile_ops.blas import contract
        rng = np.random.default_rng(99)
        x = rng.standard_normal((4, 5))
        y = rng.standard_normal((5, 6))
        if which == "x":
            x = x + 1j * rng.standard_normal((4, 5))
        else:
            y = y + 1j * rng.standard_normal((5, 6))
        got = np.asarray(contract("ab,bd->ad", jnp.asarray(x), jnp.asarray(y)))
        np.testing.assert_allclose(got, x @ y, rtol=1e-12, atol=1e-12)

    def test_knob_validation_rejects_typo(self):
        import dlaf_tpu.config as config
        with pytest.raises(ValueError, match="f64_gemm"):
            config.initialize(config.Configuration(f64_gemm="MXU"))
        config.initialize()


class TestComplex128:
    def test_matmul_c128(self):
        from dlaf_tpu.tile_ops.ozaki import matmul_c128
        rng = np.random.default_rng(13)
        a = rng.standard_normal((48, 80)) + 1j * rng.standard_normal((48, 80))
        b = rng.standard_normal((80, 32)) + 1j * rng.standard_normal((80, 32))
        got = np.asarray(matmul_c128(a, b))
        err = np.abs(got - a @ b).max()
        scale = np.abs(a).max() * np.abs(b).max() * 80
        assert err / scale < 8 * EPS

    def test_herk_c128(self):
        from dlaf_tpu.tile_ops.ozaki import herk_c128
        rng = np.random.default_rng(14)
        a = rng.standard_normal((40, 64)) + 1j * rng.standard_normal((40, 64))
        got = np.asarray(herk_c128(a))
        ref = a @ a.conj().T
        assert np.abs(got - ref).max() / (np.abs(a).max() ** 2 * 64) < 8 * EPS
        # Hermitian with exactly-real diagonal by construction
        assert np.abs(np.imag(np.diagonal(got))).max() == 0.0

    def test_blas_herk_complex_under_knob(self, monkeypatch):
        monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
        monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "8")
        import dlaf_tpu.config as config
        config.initialize()
        try:
            from dlaf_tpu.tile_ops import blas as tb
            rng = np.random.default_rng(15)
            a = rng.standard_normal((32, 48)) + 1j * rng.standard_normal((32, 48))
            c = rng.standard_normal((32, 32)) + 1j * rng.standard_normal((32, 32))
            got = np.asarray(tb.herk("L", "N", a, c, alpha=-1.0))
            full = -a @ a.conj().T + c
            ref = np.tril(full) + np.triu(c, 1)
            ref = ref - np.diag(1j * np.imag(np.diag(ref)))
            np.testing.assert_allclose(got, ref, rtol=1e-12, atol=1e-11)
        finally:
            monkeypatch.delenv("DLAF_F64_GEMM")
            monkeypatch.delenv("DLAF_F64_GEMM_MIN_DIM")
            config.initialize()


class TestF64GemmKnob:
    """f64_gemm="mxu" reroutes the level-3 tile ops through the int8 path
    framework-wide; config changes must invalidate cached programs."""

    def _with_knob(self, monkeypatch, min_dim="8"):
        monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
        monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", min_dim)
        import dlaf_tpu.config as config
        config.initialize()
        return config

    def test_blas_ops_route_and_match(self, monkeypatch):
        config = self._with_knob(monkeypatch)
        try:
            from dlaf_tpu.tile_ops import blas as tb
            rng = np.random.default_rng(5)
            a = rng.standard_normal((64, 48))
            b = rng.standard_normal((48, 32))
            c = rng.standard_normal((64, 32))
            got = np.asarray(tb.gemm(a, b, c, alpha=2.0, beta=1.0))
            np.testing.assert_allclose(got, 2.0 * (a @ b) + c,
                                       rtol=1e-13, atol=1e-12)
            h = rng.standard_normal((64, 64))
            got = np.asarray(tb.herk("L", "N", a, h, alpha=-1.0))
            ref = np.tril(-a @ a.T + h) + np.triu(h, 1)
            np.testing.assert_allclose(got, ref, rtol=1e-13, atol=1e-12)
        finally:
            monkeypatch.delenv("DLAF_F64_GEMM")
            monkeypatch.delenv("DLAF_F64_GEMM_MIN_DIM")
            config.initialize()

    def test_small_dims_stay_native(self, monkeypatch):
        config = self._with_knob(monkeypatch, min_dim="128")
        try:
            from dlaf_tpu.tile_ops.blas import _mxu_f64
            import jax.numpy as jnp2
            a = jnp2.zeros((64, 64), jnp2.float64)
            assert not _mxu_f64(a, a, dims=(64, 64, 64))
            b = jnp2.zeros((256, 256), jnp2.float64)
            assert _mxu_f64(b, b, dims=(256, 256, 256))
            f = jnp2.zeros((256, 256), jnp2.float32)
            assert not _mxu_f64(f, f, dims=(256, 256, 256))
        finally:
            monkeypatch.delenv("DLAF_F64_GEMM")
            monkeypatch.delenv("DLAF_F64_GEMM_MIN_DIM")
            config.initialize()

    @pytest.mark.parametrize("uplo", ["L", "U"])
    @pytest.mark.parametrize("dtype", [np.float64, np.complex128])
    def test_distributed_cholesky_under_knob(self, uplo, dtype, monkeypatch,
                                             devices8):
        """Distributed path: int8-MXU trailing contraction (real AND complex
        compositions) + mixed-precision panels (real, via f64_trsm)."""
        monkeypatch.setenv("DLAF_F64_TRSM", "mixed")
        config = self._with_knob(monkeypatch)
        try:
            from dlaf_tpu.algorithms.cholesky import cholesky
            from dlaf_tpu.comm.grid import Grid
            from dlaf_tpu.common.index2d import (GlobalElementSize,
                                                 TileElementSize)
            from dlaf_tpu.matrix.matrix import Matrix
            from dlaf_tpu.miniapp.generators import hpd_element_fn

            n, nb = 64, 16
            mat = Matrix.from_element_fn(
                hpd_element_fn(n, dtype), GlobalElementSize(n, n),
                TileElementSize(nb, nb), dtype=dtype, grid=Grid(2, 4))
            out = cholesky(uplo, mat)
            f = out.to_numpy()
            a = mat.to_numpy()
            tri = np.tril(f) if uplo == "L" else np.triu(f)
            rec = tri @ tri.conj().T if uplo == "L" else tri.conj().T @ tri
            resid = np.linalg.norm(rec - a) / np.linalg.norm(a)
            assert resid < 60 * n * EPS
        finally:
            monkeypatch.delenv("DLAF_F64_GEMM")
            monkeypatch.delenv("DLAF_F64_GEMM_MIN_DIM")
            monkeypatch.delenv("DLAF_F64_TRSM")
            config.initialize()

    def test_config_change_clears_registered_caches(self):
        import dlaf_tpu.config as config

        calls = []

        class FakeCached:
            def cache_clear(self):
                calls.append("cleared")

        fake = FakeCached()
        config.register_program_cache(fake)
        try:
            config.initialize()
            base = len(calls)
            cfg = config.Configuration(f64_gemm="mxu")
            config.initialize(cfg)      # differs -> must clear
            assert len(calls) == base + 1
            config.initialize(cfg)      # identical -> no clear
            assert len(calls) == base + 1
            config.initialize()         # back to defaults -> clear again
            assert len(calls) == base + 2
        finally:
            config._PROGRAM_CACHES.remove(fake)
            config.initialize()


class TestMixedPanel:
    @staticmethod
    def _spd(n, seed, cond_boost=0.0):
        rng = np.random.default_rng(seed)
        q, _ = np.linalg.qr(rng.standard_normal((n, n)))
        ev = np.linspace(1.0, 10.0 + cond_boost, n)
        return (q * ev) @ q.T

    @pytest.mark.parametrize("uplo", ["L", "U"])
    def test_potrf_refined_f64_grade(self, uplo):
        a = self._spd(96, 3)
        fac = np.asarray(potrf_refined(uplo, jnp.asarray(a)))
        rec = fac @ fac.T if uplo == "L" else fac.T @ fac
        assert np.linalg.norm(rec - a) / np.linalg.norm(a) < 96 * 4 * EPS
        # opposite triangle zeroed
        off = np.triu(fac, 1) if uplo == "L" else np.tril(fac, -1)
        assert np.all(off == 0)

    def test_potrf_refined_cond_guard_falls_back(self):
        # kappa ~ 1e8: one Newton step cannot reach the 60 n eps budget
        # (residual ~ 6e-16 * kappa), so the conditioning guard must route
        # to the native branch and keep the residual at f64 grade
        n = 128
        rng = np.random.default_rng(12)
        q, _ = np.linalg.qr(rng.standard_normal((n, n)))
        ev = np.geomspace(1e-8, 1.0, n)
        a = (q * ev) @ q.T
        a = (a + a.T) / 2
        fac = np.asarray(potrf_refined("L", jnp.asarray(a)))
        resid = np.linalg.norm(fac @ fac.T - a) / np.linalg.norm(a)
        assert resid < 60 * n * EPS

    @pytest.mark.parametrize("uplo", ["L", "U"])
    def test_potrf_refined_complex128(self, uplo):
        rng = np.random.default_rng(17)
        x = rng.standard_normal((80, 80)) + 1j * rng.standard_normal((80, 80))
        a = x @ x.conj().T + 80 * np.eye(80)
        fac = np.asarray(potrf_refined(uplo, jnp.asarray(a)))
        rec = fac @ fac.conj().T if uplo == "L" else fac.conj().T @ fac
        assert np.linalg.norm(rec - a) / np.linalg.norm(a) < 80 * 8 * EPS
        d = np.diagonal(fac)
        assert np.abs(np.imag(d)).max() == 0.0   # factor diagonal stays real

    def test_tri_inv_refined_complex128(self):
        rng = np.random.default_rng(18)
        l = np.tril(rng.standard_normal((64, 64))
                    + 1j * rng.standard_normal((64, 64))) + 8 * np.eye(64)
        inv = np.asarray(tri_inv_refined(jnp.asarray(l), lower=True))
        # complex rounding carries a ~2x larger constant than the real case
        assert np.linalg.norm(inv @ l - np.eye(64)) < 64 * 32 * EPS

    def test_potrf_refined_fallback_on_f32_failure(self):
        # PD in f64 but singular at f32: the off-diagonal rounds to 1.0
        a = np.array([[1.0, 1.0 - 5e-9], [1.0 - 5e-9, 1.0]])
        fac = np.asarray(potrf_refined("L", jnp.asarray(a)))
        assert np.isfinite(fac).all()
        assert np.linalg.norm(fac @ fac.T - a) < 1e-14

    def test_tri_inv_refined(self):
        rng = np.random.default_rng(4)
        l = np.tril(rng.standard_normal((64, 64))) + 8 * np.eye(64)
        inv = np.asarray(tri_inv_refined(jnp.asarray(l), lower=True))
        assert np.linalg.norm(inv @ l - np.eye(64)) < 64 * 8 * EPS
        u = l.T
        invu = np.asarray(tri_inv_refined(jnp.asarray(u), lower=False))
        assert np.linalg.norm(invu @ u - np.eye(64)) < 64 * 8 * EPS

    @pytest.mark.parametrize("uplo", ["L", "U"])
    @pytest.mark.parametrize("cplx", [False, True])
    def test_potrf_inv_refined_fused(self, uplo, cplx):
        """The fused (factor, inverse) step must match potrf_refined's
        factor contract AND deliver an f64-grade explicit inverse."""
        from dlaf_tpu.tile_ops.mixed import potrf_inv_refined

        n = 96
        if cplx:
            rng = np.random.default_rng(23)
            x = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
            a = x @ x.conj().T + n * np.eye(n)
        else:
            a = self._spd(n, 7)
        fac, inv = (np.asarray(z)
                    for z in potrf_inv_refined(uplo, jnp.asarray(a)))
        rec = fac @ fac.conj().T if uplo == "L" else fac.conj().T @ fac
        assert np.linalg.norm(rec - a) / np.linalg.norm(a) < n * 8 * EPS
        assert np.linalg.norm(inv @ fac - np.eye(n)) < n * 32 * EPS
        tri = np.tril if uplo == "L" else np.triu
        assert np.all(fac == tri(fac)) and np.all(inv == tri(inv))

    @pytest.mark.parametrize("uplo", ["L", "U"])
    @pytest.mark.parametrize("n", [96, 256, 100])  # incl. odd split sizes
    def test_recursive_seed_matches_xla_seed(self, uplo, n, monkeypatch):
        """mixed_seed="recursive" (trace-time block recursion, gemm-only
        above the leaves) must deliver the same f64-grade contracts as the
        native XLA seed."""
        import dlaf_tpu.config as config
        from dlaf_tpu.tile_ops.mixed import potrf_inv_refined

        a = self._spd(n, n + 5)
        monkeypatch.setenv("DLAF_MIXED_SEED", "recursive")
        monkeypatch.setenv("DLAF_MIXED_SEED_BASE", "32")
        config.initialize()
        try:
            fac, inv = (np.asarray(z)
                        for z in potrf_inv_refined(uplo, jnp.asarray(a)))
        finally:
            monkeypatch.delenv("DLAF_MIXED_SEED")
            monkeypatch.delenv("DLAF_MIXED_SEED_BASE")
            config.initialize()
        rec = fac @ fac.T if uplo == "L" else fac.T @ fac
        assert np.linalg.norm(rec - a) / np.linalg.norm(a) < n * 8 * EPS
        assert np.linalg.norm(inv @ fac - np.eye(n)) < n * 32 * EPS

    def test_recursive_seed_complex_and_fallback(self, monkeypatch):
        import dlaf_tpu.config as config
        from dlaf_tpu.tile_ops.mixed import potrf_inv_refined

        monkeypatch.setenv("DLAF_MIXED_SEED", "recursive")
        config.initialize()
        try:
            n = 80
            rng = np.random.default_rng(41)
            x = rng.standard_normal((n, n)) + 1j * rng.standard_normal((n, n))
            a = x @ x.conj().T + n * np.eye(n)
            fac, inv = (np.asarray(z)
                        for z in potrf_inv_refined("L", jnp.asarray(a)))
            assert (np.linalg.norm(fac @ fac.conj().T - a)
                    / np.linalg.norm(a) < n * 8 * EPS)
            assert np.linalg.norm(inv @ fac - np.eye(n)) < n * 64 * EPS
            # ill-conditioned block: guard must still route to native
            q, _ = np.linalg.qr(rng.standard_normal((128, 128)))
            ev = np.geomspace(1e-8, 1.0, 128)
            b = (q * ev) @ q.T
            b = (b + b.T) / 2
            fb, _ = (np.asarray(z)
                     for z in potrf_inv_refined("L", jnp.asarray(b)))
            assert (np.linalg.norm(fb @ fb.T - b) / np.linalg.norm(b)
                    < 60 * 128 * EPS)
        finally:
            monkeypatch.delenv("DLAF_MIXED_SEED")
            config.initialize()

    def test_potrf_inv_refined_cond_fallback(self):
        from dlaf_tpu.tile_ops.mixed import potrf_inv_refined

        n = 128
        rng = np.random.default_rng(29)
        q, _ = np.linalg.qr(rng.standard_normal((n, n)))
        ev = np.geomspace(1e-8, 1.0, n)
        a = (q * ev) @ q.T
        a = (a + a.T) / 2
        fac, inv = (np.asarray(z)
                    for z in potrf_inv_refined("L", jnp.asarray(a)))
        assert np.linalg.norm(fac @ fac.T - a) / np.linalg.norm(a) < 60 * n * EPS
        assert np.isfinite(inv).all()


class TestCholeskyOzakiPath:
    @pytest.mark.parametrize("uplo", ["L", "U"])
    def test_local_complex128(self, uplo, monkeypatch):
        """trailing='ozaki' with complex128: herk_c128 trailing + complex
        mixed panels (c64 seed)."""
        monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "ozaki")
        import dlaf_tpu.config as config
        config.initialize()
        try:
            from dlaf_tpu.algorithms.cholesky import cholesky
            from dlaf_tpu.common.index2d import (GlobalElementSize,
                                                 TileElementSize)
            from dlaf_tpu.matrix.matrix import Matrix
            from dlaf_tpu.miniapp.generators import hpd_element_fn

            n, nb = 192, 64
            mat = Matrix.from_element_fn(
                hpd_element_fn(n, np.complex128), GlobalElementSize(n, n),
                TileElementSize(nb, nb), dtype=np.complex128)
            out = cholesky(uplo, mat)
            f = out.to_numpy()
            a = mat.to_numpy()
            tri = np.tril(f) if uplo == "L" else np.triu(f)
            rec = tri @ tri.conj().T if uplo == "L" else tri.conj().T @ tri
            resid = np.linalg.norm(rec - a) / np.linalg.norm(a)
            assert resid < 60 * n * EPS
        finally:
            monkeypatch.delenv("DLAF_CHOLESKY_TRAILING")
            config.initialize()

    @pytest.mark.parametrize("n,nb,uplo", [(256, 64, "L"), (256, 64, "U"),
                                           (150, 64, "L")])
    def test_local_residual(self, n, nb, uplo, monkeypatch):
        monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "ozaki")
        import dlaf_tpu.config as config
        config.initialize()
        try:
            from dlaf_tpu.algorithms.cholesky import cholesky
            from dlaf_tpu.common.index2d import (GlobalElementSize,
                                                 TileElementSize)
            from dlaf_tpu.matrix.matrix import Matrix
            from dlaf_tpu.miniapp.generators import hpd_element_fn

            mat = Matrix.from_element_fn(
                hpd_element_fn(n, np.float64), GlobalElementSize(n, n),
                TileElementSize(nb, nb), dtype=np.float64)
            out = cholesky(uplo, mat)
            f = out.to_numpy()
            a = mat.to_numpy()
            tri = np.tril(f) if uplo == "L" else np.triu(f)
            rec = tri @ tri.T if uplo == "L" else tri.T @ tri
            resid = np.linalg.norm(rec - a) / np.linalg.norm(a)
            assert resid < 60 * n * EPS
            # untouched triangle passes through
            other = np.triu(mat.to_numpy(), 1) if uplo == "L" \
                else np.tril(mat.to_numpy(), -1)
            got_other = np.triu(f, 1) if uplo == "L" else np.tril(f, -1)
            np.testing.assert_array_equal(got_other, other)
        finally:
            monkeypatch.delenv("DLAF_CHOLESKY_TRAILING")
            config.initialize()

    def test_non_f64_falls_back(self, monkeypatch):
        # f32 input under trailing="ozaki" must still work (static fallback)
        monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "ozaki")
        import dlaf_tpu.config as config
        config.initialize()
        try:
            from dlaf_tpu.algorithms.cholesky import cholesky
            from dlaf_tpu.common.index2d import (GlobalElementSize,
                                                 TileElementSize)
            from dlaf_tpu.matrix.matrix import Matrix
            from dlaf_tpu.miniapp.generators import hpd_element_fn

            n = 128
            mat = Matrix.from_element_fn(
                hpd_element_fn(n, np.float32), GlobalElementSize(n, n),
                TileElementSize(64, 64), dtype=np.float32)
            out = cholesky("L", mat)
            f = np.tril(out.to_numpy())
            resid = np.linalg.norm(f @ f.T - mat.to_numpy())
            assert resid / np.linalg.norm(mat.to_numpy()) < 60 * n * 1.2e-7
        finally:
            monkeypatch.delenv("DLAF_CHOLESKY_TRAILING")
            config.initialize()


class TestBf16DotRoute:
    """ozaki_dot="bf16": slice contractions over the native bf16 MXU path
    must be BIT-IDENTICAL to the int8 route (7-bit slices are exact in
    bf16; f32 accumulation is integer-exact while k*2^12 <= 2^24, int32
    chunk sums beyond)."""

    @pytest.mark.parametrize("m,k", [(64, 48), (33, 256), (16, 5000)])
    def test_matmul_bitwise_equal(self, m, k, monkeypatch):
        rng = np.random.default_rng(5)
        a = rng.standard_normal((m, k)) * 10.0 ** rng.integers(-6, 6, (m, 1))
        b = rng.standard_normal((k, m)) * 10.0 ** rng.integers(-6, 6, (1, m))
        from dlaf_tpu import config

        ref = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
        monkeypatch.setenv("DLAF_OZAKI_DOT", "bf16")
        config.initialize()
        try:
            got = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
        finally:
            monkeypatch.delenv("DLAF_OZAKI_DOT")
            config.initialize()
        assert got.tobytes() == ref.tobytes()

    def test_syrk_bitwise_equal(self, monkeypatch):
        rng = np.random.default_rng(6)
        a = rng.standard_normal((96, 128))
        from dlaf_tpu import config

        ref = np.asarray(syrk_f64(jnp.asarray(a)))
        monkeypatch.setenv("DLAF_OZAKI_DOT", "bf16")
        config.initialize()
        try:
            got = np.asarray(syrk_f64(jnp.asarray(a)))
        finally:
            monkeypatch.delenv("DLAF_OZAKI_DOT")
            config.initialize()
        assert got.tobytes() == ref.tobytes()


class TestConcatGroupRoute:
    """ozaki_group="concat": one k-concatenated dot per shift group must be
    BIT-IDENTICAL to the per-pair "dots" form — the concatenated
    contraction is exactly the sum of the per-pair contractions, in exact
    integer arithmetic on every route (int8 i32-accumulated, bf16
    f32-chunk-accumulated)."""

    def _ab(self, monkeypatch, fn, *args, dot=None):
        from dlaf_tpu import config

        if dot is not None:
            monkeypatch.setenv("DLAF_OZAKI_DOT", dot)
        # pin the reference arm to "dots" explicitly: the default is
        # "auto" (concat on TPU), which would make this A/B vacuous on
        # exactly the platform where concat is the production form
        monkeypatch.setenv("DLAF_OZAKI_GROUP", "dots")
        config.initialize()
        try:
            ref = np.asarray(fn(*args))
            monkeypatch.setenv("DLAF_OZAKI_GROUP", "concat")
            config.initialize()
            got = np.asarray(fn(*args))
        finally:
            monkeypatch.delenv("DLAF_OZAKI_GROUP", raising=False)
            if dot is not None:
                monkeypatch.delenv("DLAF_OZAKI_DOT")
            config.initialize()
        assert got.tobytes() == ref.tobytes()

    @pytest.mark.parametrize("dot", ["int8", "bf16"])
    @pytest.mark.parametrize("m,k,s", [(64, 48, 7), (33, 256, 8),
                                       (16, 5000, 6)])
    def test_matmul_bitwise_equal(self, m, k, s, dot, monkeypatch):
        rng = np.random.default_rng(12)
        a = rng.standard_normal((m, k)) * 10.0 ** rng.integers(-6, 6, (m, 1))
        b = rng.standard_normal((k, m)) * 10.0 ** rng.integers(-6, 6, (1, m))
        self._ab(monkeypatch, lambda x, y: matmul_f64(x, y, slices=s),
                 jnp.asarray(a), jnp.asarray(b), dot=dot)

    @pytest.mark.parametrize("dot", ["int8", "bf16"])
    @pytest.mark.parametrize("s", [7, 8])
    def test_syrk_bitwise_equal(self, s, dot, monkeypatch):
        rng = np.random.default_rng(13)
        a = rng.standard_normal((96, 128)) * 10.0 ** rng.integers(-4, 4,
                                                                  (96, 1))
        self._ab(monkeypatch, lambda x: syrk_f64(x, slices=s),
                 jnp.asarray(a), dot=dot)

    def test_accuracy_f64_grade_under_concat(self, monkeypatch):
        # same budget as TestOzaki.test_accuracy_f64_grade, via the knob
        from dlaf_tpu import config

        rng = np.random.default_rng(14)
        a = rng.standard_normal((40, 64))
        b = rng.standard_normal((64, 40))
        monkeypatch.setenv("DLAF_OZAKI_GROUP", "concat")
        config.initialize()
        try:
            got = np.asarray(matmul_f64(jnp.asarray(a), jnp.asarray(b)))
        finally:
            monkeypatch.delenv("DLAF_OZAKI_GROUP")
            config.initialize()
        ref = a @ b
        scale = (np.abs(a).max(axis=-1)[:, None]
                 * np.abs(b).max(axis=-2)[None, :] * a.shape[-1])
        assert (np.abs(got - ref) / scale).max() < 4 * EPS

    def test_distributed_cholesky_mxu_under_concat(self, monkeypatch,
                                                   devices8):
        """The distributed mxu trailing einsums route through the same
        matmul/syrk entry points, so group=concat must hold there too —
        different contraction shapes (batched tile axes) than the local
        arms above."""
        from dlaf_tpu import config

        monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
        monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "8")
        monkeypatch.setenv("DLAF_F64_TRSM", "mixed")
        monkeypatch.setenv("DLAF_OZAKI_GROUP", "concat")
        config.initialize()
        try:
            from dlaf_tpu.algorithms.cholesky import cholesky
            from dlaf_tpu.comm.grid import Grid
            from dlaf_tpu.common.index2d import (GlobalElementSize,
                                                 TileElementSize)
            from dlaf_tpu.matrix.matrix import Matrix
            from dlaf_tpu.miniapp.generators import hpd_element_fn

            n, nb = 64, 16
            mat = Matrix.from_element_fn(
                hpd_element_fn(n, np.float64), GlobalElementSize(n, n),
                TileElementSize(nb, nb), dtype=np.float64, grid=Grid(2, 4))
            f = cholesky("L", mat).to_numpy()
            a = mat.to_numpy()
            tri = np.tril(f)
            resid = np.linalg.norm(tri @ tri.T - a) / np.linalg.norm(a)
            assert resid < 60 * n * EPS
        finally:
            for k in ("DLAF_F64_GEMM", "DLAF_F64_GEMM_MIN_DIM",
                      "DLAF_F64_TRSM", "DLAF_OZAKI_GROUP"):
                monkeypatch.delenv(k)
            config.initialize()


class TestScanAccumRoute:
    """ozaki_accum="scan" (lax.scan'd zero-padded shift groups, O(1) live
    partials) must be BIT-IDENTICAL to the straight-line "xla" schedule
    under the concat group form — the padded columns are int8 zeros,
    which contribute exactly nothing on either dot route, and the f64
    carry folds groups in the same order with the same scales."""

    def _ab(self, monkeypatch, fn, *args, dot):
        from dlaf_tpu import config

        monkeypatch.setenv("DLAF_OZAKI_GROUP", "concat")
        monkeypatch.setenv("DLAF_OZAKI_DOT", dot)
        monkeypatch.setenv("DLAF_OZAKI_ACCUM", "xla")
        config.initialize()
        try:
            ref = np.asarray(fn(*args))
            monkeypatch.setenv("DLAF_OZAKI_ACCUM", "scan")
            config.initialize()
            got = np.asarray(fn(*args))
        finally:
            for k in ("DLAF_OZAKI_GROUP", "DLAF_OZAKI_DOT",
                      "DLAF_OZAKI_ACCUM"):
                monkeypatch.delenv(k, raising=False)
            config.initialize()
        assert got.tobytes() == ref.tobytes()

    @pytest.mark.parametrize("dot", ["int8", "bf16"])
    @pytest.mark.parametrize("m,k,s", [(64, 48, 7), (33, 256, 8),
                                       (16, 700, 6)])
    def test_matmul_bitwise_equal(self, m, k, s, dot, monkeypatch):
        rng = np.random.default_rng(21)
        a = rng.standard_normal((m, k)) * 10.0 ** rng.integers(-6, 6, (m, 1))
        b = rng.standard_normal((k, m)) * 10.0 ** rng.integers(-6, 6, (1, m))
        self._ab(monkeypatch, lambda x, y: matmul_f64(x, y, slices=s),
                 jnp.asarray(a), jnp.asarray(b), dot=dot)

    @pytest.mark.parametrize("dot", ["int8", "bf16"])
    @pytest.mark.parametrize("s", [7, 8])
    def test_syrk_bitwise_equal(self, s, dot, monkeypatch):
        rng = np.random.default_rng(22)
        a = rng.standard_normal((96, 128)) * 10.0 ** rng.integers(-4, 4,
                                                                  (96, 1))
        self._ab(monkeypatch, lambda x: syrk_f64(x, slices=s),
                 jnp.asarray(a), dot=dot)

    def test_auto_resolves_per_platform(self, monkeypatch):
        """ozaki_accum="auto" (the default): scan on TPU — the measured
        winner of the session-4d A/B (119.6 vs 112.8 GF/s at N=4096 with
        an O(1) live-partials bound) — and the straight-line xla schedule
        elsewhere; explicit values pass through untouched."""
        import jax

        from dlaf_tpu import config
        from dlaf_tpu.obs.logging import forget_once as _forget_once
        from dlaf_tpu.obs.logging import once_seen_keys as _once_keys
        from dlaf_tpu.tile_ops.ozaki import _accum_impl

        keys = [("ozaki_accum", b, c) for b, c in
                (("cpu", "xla"), ("tpu", "scan"))]
        pre = {k for k in keys if k in _once_keys("config")}
        config.initialize()  # bare default: auto
        try:
            assert _accum_impl() == "xla"     # suite runs on CPU
            monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
            assert _accum_impl() == "scan"
            monkeypatch.setenv("DLAF_OZAKI_ACCUM", "xla")
            config.initialize()
            assert _accum_impl() == "xla"     # explicit outranks auto
        finally:
            monkeypatch.delenv("DLAF_OZAKI_ACCUM", raising=False)
            for k in keys:
                if k not in pre:
                    _forget_once("config", k)
            config.initialize()

    def test_accuracy_under_jit(self, monkeypatch):
        """The scan schedule composes with jit and stays f64-grade."""
        import jax

        from dlaf_tpu import config

        monkeypatch.setenv("DLAF_OZAKI_GROUP", "concat")
        monkeypatch.setenv("DLAF_OZAKI_ACCUM", "scan")
        config.initialize()
        try:
            rng = np.random.default_rng(23)
            a = rng.standard_normal((64, 96))
            got = np.asarray(jax.jit(
                lambda x: syrk_f64(x, slices=8))(jnp.asarray(a)))
            np.testing.assert_allclose(got, a @ a.T, rtol=1e-14, atol=1e-12)
        finally:
            monkeypatch.delenv("DLAF_OZAKI_GROUP")
            monkeypatch.delenv("DLAF_OZAKI_ACCUM")
            config.initialize()


@pytest.mark.parametrize("accum", ["xla", "scan"])
def test_concat_syrk_int32_wrap_window(accum, monkeypatch):
    """The concat syrk's elementwise pair sum (g + g.T + diag) must not
    wrap int32 in the window where s*k*2^12 >= 2^31 but the half-concat
    depth stays below _dot_i8's own f64-chunking threshold. Adversarial
    rows: a decoy max of 129/128 makes every unit element normalize to
    64/129, whose base-128 expansion has balanced digits of EXACTLY
    +-64 at every level — so each pair dot reaches ~2^28 and a 4-pair
    half-group sum crosses 2^31 on the unguarded path."""
    from dlaf_tpu import config

    monkeypatch.setenv("DLAF_OZAKI_GROUP", "concat")
    monkeypatch.setenv("DLAF_OZAKI_ACCUM", accum)
    config.initialize()
    try:
        # 65543 unit columns: the d=7 half-group sum reaches
        # -2*4*4096*65543 = -(2^31) - 229376, strictly past INT32_MIN
        # (65536 columns land at exactly -2^31, which still represents)
        k = (1 << 16) + 8
        a = np.ones((8, k))
        a[:, 0] = 129.0 / 128.0
        got = np.asarray(syrk_f64(jnp.asarray(a), slices=8))
        ref = a @ a.T
        np.testing.assert_allclose(got, ref, rtol=1e-12)
    finally:
        monkeypatch.delenv("DLAF_OZAKI_GROUP")
        monkeypatch.delenv("DLAF_OZAKI_ACCUM")
        config.initialize()


class TestPeelBoundaryRegression:
    """Regression net for the round-4 peel-corruption class (commit
    0807ec7): the TPU f64-emulation's `round` mis-rounds tie+epsilon
    values (measured on-silicon: round(17.5000005) = 19), the one-unit
    overshoot pushed the next residual*scale outside int8, and the
    f32->s8 saturation rail then pinned every later slice — shipping a
    ~2^-8 decomposition error through three rounds of green CPU tests.
    The hardened peel (native f32 round + subtracting the STORED slice
    value) is platform-independent code; these properties pin its two
    invariants at exactly the boundary values that broke, so any future
    peel change that reopens the class fails HERE, not on silicon.
    (The per-window primitive behavior itself is asserted on hardware by
    scripts/tpu_prec_probe.py's prim_* arm.)
    """

    def _reconstruct(self, sl):
        from dlaf_tpu.tile_ops.ozaki import SLICE_BITS

        return sum(sl[t].astype(np.float64) * 2.0 ** (-SLICE_BITS * (t + 1))
                   for t in range(sl.shape[0]))

    @pytest.mark.parametrize("eps", [0.0, 5e-7, -5e-7, 1e-9, -1e-9])
    def test_tie_epsilon_values_stay_inside_rail(self, eps):
        """Every first-slice tie (k+1/2)/128 plus the measured corruption
        epsilons: all 8 slices inside the +-65 rail (|I|<=64 plus at most
        one absorbable overshoot unit — NOT pinned at the +-127 cast
        rail), and the stored slices reconstruct xn to the 56-bit
        budget."""
        import jax

        from dlaf_tpu.tile_ops import ozaki as oz

        ks = np.arange(-64, 64)
        xn_host = np.clip((ks + 0.5 + eps) / 128.0, -0.5, 0.5)
        slices = jax.jit(lambda v: jnp.stack(oz._peel_slices(v, 8)))(
            jnp.asarray(xn_host))
        sl = np.asarray(slices, dtype=np.int64)
        assert np.abs(sl).max() <= 65, \
            f"slice outside rail: {np.abs(sl).max()} (saturation cascade)"
        err = np.abs(self._reconstruct(sl) - xn_host).max()
        assert err < 2.0 ** -53, f"reconstruction off budget: {err}"

    def test_slice_residual_consistency_random(self):
        """Random normalized blocks: slice/residual consistency means the
        stored int8 values alone reconstruct xn to the budget — whatever
        unit choices the platform's rounding made along the way."""
        import jax

        from dlaf_tpu.tile_ops import ozaki as oz

        rng = np.random.default_rng(23)
        xn_host = rng.uniform(-0.5, 0.5, size=(64, 64))
        slices = jax.jit(lambda v: jnp.stack(oz._peel_slices(v, 8)))(
            jnp.asarray(xn_host))
        sl = np.asarray(slices, dtype=np.int64)
        assert np.abs(sl).max() <= 65
        err = np.abs(self._reconstruct(sl) - xn_host).max()
        # 8 slices x 7 bits = 56 kept bits; the dropped residual is
        # < 2^-57 of the normalized scale
        assert err < 2.0 ** -56, f"reconstruction off budget: {err}"
