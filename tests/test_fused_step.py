"""Fused Cholesky STEP kernel (``step_impl``, docs/pallas_panel.md).

Interpret-mode exactness suite for the fused step route
(tile_ops/pallas_panel.py ``fused_step`` / ``fused_factor_solve``):
kernel-vs-composed-ops parity within the documented c*n*eps bound across
uplo x {f32, bf16}, the ``potrf_info`` NaN-prefix contract preserved
(the fused kernel's factor is bitwise the fused_potrf ladder's), the
bitwise ``cholesky_lookahead``/``comm_lookahead``/``with_info``
contracts WITHIN the fused-step route, the ``site="step"`` degradation
accounting (unsupported dtype / VMEM budget / ``inject.disable_route``,
strict-raising), the ``dlaf_step_kernel_total{impl}`` trace-time
counter, and the jaxpr pins: ONE pallas_call per strip-bearing step on
the fused-step route, with the PR-4 comm-overlap independence pins
holding under ``step_impl=fused``.

The accelerator tunnel is still wedged, so interpret mode is the only
on-container validation path — these pins are load-bearing, mirroring
tests/test_pallas_panel.py's discipline for the panel route.
"""

import os

import numpy as np
import pytest
import scipy.linalg as sla

import jax
import jax.numpy as jnp

import dlaf_tpu.config as C
from dlaf_tpu import health, obs
from dlaf_tpu.analysis import depgraph
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.matrix.matrix import Matrix
from dlaf_tpu.tile_ops import blas as tb
from dlaf_tpu.tile_ops import lapack as tl
from dlaf_tpu.tile_ops import pallas_panel as ppan

#: Documented parity bound (docs/pallas_panel.md "Fused step kernel"):
#: the fused step is the same micro-block potrf ladder + explicit-
#: inverse solve + one-dot trailing slab, each backward-stable — parity
#: vs the composed op chain is c*n*eps with c~8 for well-conditioned
#: HPD test blocks, NOT bitwise.
ULP_C = 8.0


def _bound(n, dtype):
    return ULP_C * n * float(jnp.finfo(jnp.dtype(dtype)).eps)


@pytest.fixture(autouse=True)
def _reset():
    yield
    for k in ("DLAF_STEP_IMPL", "DLAF_STEP_VMEM_LIMIT", "DLAF_PANEL_IMPL",
              "DLAF_METRICS_PATH", "DLAF_CHOLESKY_LOOKAHEAD",
              "DLAF_COMM_LOOKAHEAD", "DLAF_CHOLESKY_TRAILING",
              "DLAF_DIST_STEP_MODE"):
        os.environ.pop(k, None)
    obs._reset_for_tests()
    C.finalize()
    C.initialize()


def hpd(n, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    return (x @ x.T + n * np.eye(n)).astype(dtype)


# ---------------------------------------------------------------------------
# Kernel-level parity (interpret mode)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype,rtol", [(np.float32, None),
                                        (jnp.bfloat16, 0.06)])
@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("d,m", [(8, 24), (4, 10), (16, 16), (8, 3)])
def test_fused_step_parity(uplo, d, m, dtype, rtol):
    """3-op kernel (potrf + strip solve + trailing slab) vs the composed
    chain: diag/panel/slab all within the documented bound, and the
    slab's not-yet-factored cells pass through bitwise."""
    w = min(d, m)
    a = jnp.asarray(hpd(d + m, seed=2), dtype=dtype)
    blk = a[:d, :d]
    if uplo == "L":
        strip, slab = a[d:, :d], a[d:, d:d + w]
    else:
        strip, slab = a[:d, d:], a[d:d + w, d:]
    diag, panel, nslab = ppan.fused_step(uplo, blk, strip, slab,
                                         interpret=True)
    assert (diag.dtype, panel.dtype, nslab.dtype) == (a.dtype,) * 3
    f32 = jnp.float32
    dr = tl.potrf(uplo, blk.astype(f32))
    pr = (tb.trsm("R", "L", "C", "N", dr, strip.astype(f32))
          if uplo == "L" else
          tb.trsm("L", "U", "C", "N", dr, strip.astype(f32)))
    if uplo == "L":
        mask = np.arange(m)[:, None] >= np.arange(w)[None, :]
        sr = np.asarray(slab, np.float32) - np.where(
            mask, np.asarray(pr @ jnp.conj(pr[:w]).T), 0)
    else:
        mask = np.arange(w)[:, None] <= np.arange(m)[None, :]
        sr = np.asarray(slab, np.float32) - np.where(
            mask, np.asarray(jnp.conj(pr[:, :w]).T @ pr), 0)
    tol = rtol if rtol is not None else _bound(d + m, np.float32)
    for got, ref, name in ((diag, dr, "diag"), (panel, pr, "panel"),
                           (nslab, sr, "slab")):
        err = float(np.abs(np.asarray(got, np.float32) - np.asarray(ref)
                           ).max() / max(np.abs(np.asarray(ref)).max(),
                                         1e-30))
        assert err < tol, (uplo, d, m, name, err, tol)
    # pass-through: unmasked slab cells are bitwise the input's
    sm = np.where(mask, np.asarray(slab), np.asarray(nslab))
    np.testing.assert_array_equal(sm, np.asarray(slab))


@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("batched", [False, True])
def test_fused_factor_solve_parity(uplo, batched):
    """2-op kernel (potrf + strip solve, no slab — the dist builders'
    form, where the trailing update needs the post-collective panel)."""
    d, m, r = 8, 20, 3
    a = jnp.asarray(hpd(d * (r + 1), seed=3))
    blk = a[:d, :d]
    if batched:
        strip = jnp.stack([a[(i + 1) * d:(i + 2) * d, :d] if uplo == "L"
                           else a[:d, (i + 1) * d:(i + 2) * d]
                           for i in range(r)])
    else:
        strip = a[d:d + m, :d] if uplo == "L" else a[:d, d:d + m]
    diag, pan = ppan.fused_factor_solve(uplo, blk, strip, interpret=True)
    dr = tl.potrf(uplo, blk)
    if batched:
        pr = (tb.trsm_panel("R", "L", "C", "N", dr, strip) if uplo == "L"
              else tb.trsm_panel("L", "U", "C", "N", dr, strip))
    else:
        pr = (tb.trsm("R", "L", "C", "N", dr, strip) if uplo == "L"
              else tb.trsm("L", "U", "C", "N", dr, strip))
    bound = _bound(d * (r + 1), np.float32)
    for got, ref in ((diag, dr), (pan, pr)):
        err = float(jnp.max(jnp.abs(got - ref)) / jnp.max(jnp.abs(ref)))
        assert err < bound, (uplo, batched, err)


def test_fused_step_nan_prefix_info_contract():
    """The fused step's factor block is BITWISE the fused_potrf ladder's
    — a non-positive pivot NaNs the diagonal from the failing column on,
    so the potrf_info prefix contract carries over unchanged."""
    bad = np.diag([4.0, 9.0, -1.0, 2.0, 5.0, 1.0, 1.0, 1.0]
                  ).astype(np.float32)
    strip = np.ones((16, 8), np.float32)
    slab = np.ones((16, 8), np.float32)
    diag, _, _ = ppan.fused_step("L", jnp.asarray(bad), jnp.asarray(strip),
                                 jnp.asarray(slab), interpret=True)
    ref = ppan.fused_potrf("L", jnp.asarray(bad), interpret=True)
    assert np.asarray(diag).tobytes() == np.asarray(ref).tobytes()
    _, info = tl.potrf_info("L", diag)
    assert int(np.asarray(info).ravel()[0]) == 3


def test_step_vmem_bytes_model():
    """The VMEM budget model (docs/pallas_panel.md): pad-size squares of
    the resident diag+factor (2x), the 4 double-buffered grid blocks
    (8x), and the two f32 scratch squares."""
    s = 128
    assert ppan.step_vmem_bytes(s, np.float32) == s * s * (10 * 4 + 8)
    assert ppan.step_vmem_bytes(s, jnp.bfloat16) == s * s * (10 * 2 + 8)
    # sub-pad block edges price at the padded kernel size
    assert ppan.step_vmem_bytes(8, np.float32) == \
        ppan.step_vmem_bytes(128, np.float32)
    # the default budget admits the product nb=256 f32 step kernel
    assert ppan.step_vmem_bytes(256, np.float32) \
        <= C.Configuration().step_vmem_limit


# ---------------------------------------------------------------------------
# End-to-end route parity + knob contracts
# ---------------------------------------------------------------------------

def _factor(uplo, a, nb, grid=None, **kw):
    return cholesky(uplo, Matrix.from_global(a, TileElementSize(nb, nb),
                                             grid=grid), **kw)


@pytest.mark.parametrize("trailing", ["loop", "biggemm", "scan"])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_local_route_parity(uplo, trailing, devices8, monkeypatch):
    """Fused-step vs composed route pinned within the documented bound
    across uplo x trailing (local, f32; n%nb != 0 exercises the ragged
    final block)."""
    n, nb = 21, 8
    a = hpd(n, seed=1)
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", trailing)
    outs = {}
    for impl in ("xla", "fused"):
        monkeypatch.setenv("DLAF_STEP_IMPL", impl)
        C.initialize()
        outs[impl] = np.asarray(_factor(uplo, a, nb).storage)
    scale = np.abs(outs["xla"]).max()
    assert np.abs(outs["fused"] - outs["xla"]).max() / scale \
        < _bound(n, np.float32)


@pytest.mark.parametrize("trailing", ["loop", "scan"])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_dist_route_parity(uplo, trailing, devices8, monkeypatch):
    """Fused-step vs composed route on the 2x2 dist builders (unrolled
    and scan step modes)."""
    n, nb = 24, 8
    a = hpd(n, seed=6)
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", trailing)
    outs = {}
    for impl in ("xla", "fused"):
        monkeypatch.setenv("DLAF_STEP_IMPL", impl)
        C.initialize()
        outs[impl] = np.asarray(_factor(uplo, a, nb,
                                        grid=Grid(2, 2)).storage)
    scale = np.abs(outs["xla"]).max()
    assert np.abs(outs["fused"] - outs["xla"]).max() / scale \
        < _bound(n, np.float32)


def test_local_bf16_fused_step(monkeypatch):
    """bf16 end-to-end on the fused-step route (the kernel computes in
    f32 and casts back) against the f32 reference factor."""
    n, nb = 24, 8
    a16 = jnp.asarray(hpd(n, seed=1), dtype=jnp.bfloat16)
    monkeypatch.setenv("DLAF_STEP_IMPL", "fused")
    # the final (strip-less) step has no fused-step kernel; its potrf
    # rides the panel route, which must also be fused for bf16 on CPU
    monkeypatch.setenv("DLAF_PANEL_IMPL", "fused")
    C.initialize()
    out = _factor("L", a16, nb)
    ref = sla.cholesky(np.asarray(a16, dtype=np.float32) + 0.0,
                       lower=True)
    got = np.tril(np.asarray(out.to_numpy(), dtype=np.float32))
    assert np.abs(got - ref).max() / np.abs(ref).max() < 0.06


@pytest.mark.parametrize("trailing", ["loop", "scan"])
@pytest.mark.parametrize("grid_shape", [None, (2, 2)])
def test_lookahead_bitwise_under_fused_step(trailing, grid_shape,
                                            devices8, monkeypatch):
    """cholesky_lookahead (and comm_lookahead, dist) stay BITWISE
    transparent on the fused-step route — the fused branch always uses
    the split-trailing structure, so the knobs only change carry-vs-
    re-read of identical values."""
    n, nb = 24, 8
    a = hpd(n, seed=4)
    grid = Grid(*grid_shape) if grid_shape else None
    monkeypatch.setenv("DLAF_STEP_IMPL", "fused")
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", trailing)
    outs = {}
    for la in ("0", "1"):
        monkeypatch.setenv("DLAF_CHOLESKY_LOOKAHEAD", la)
        monkeypatch.setenv("DLAF_COMM_LOOKAHEAD", la)
        C.initialize()
        outs[la] = np.asarray(_factor("L", a, nb, grid=grid).storage)
    assert outs["0"].tobytes() == outs["1"].tobytes()


def test_with_info_bitwise_under_fused_step(devices8, monkeypatch):
    """The factor is bitwise identical with with_info on or off on the
    fused-step route (info is a pure extra output over the same
    kernels)."""
    a = hpd(24, seed=5)
    monkeypatch.setenv("DLAF_STEP_IMPL", "fused")
    C.initialize()
    for grid in (None, Grid(2, 2)):
        plain = np.asarray(_factor("L", a, 8, grid=grid).storage)
        f, info = _factor("L", a, 8, grid=grid, with_info=True)
        assert int(info) == 0
        assert np.asarray(f.storage).tobytes() == plain.tobytes()


def test_composes_with_fused_panel(monkeypatch):
    """step_impl=fused + panel_impl=fused: the final (strip-less) step
    still routes its potrf through the fused panel kernel; parity
    holds."""
    n, nb = 21, 8
    a = hpd(n, seed=9)
    monkeypatch.setenv("DLAF_STEP_IMPL", "fused")
    monkeypatch.setenv("DLAF_PANEL_IMPL", "fused")
    C.initialize()
    out = np.asarray(_factor("L", a, nb).to_numpy())
    ref = sla.cholesky(a, lower=True)
    assert np.abs(np.tril(out) - ref).max() / np.abs(ref).max() \
        < _bound(n, np.float32)


# ---------------------------------------------------------------------------
# Degradation accounting (site="step") + counters
# ---------------------------------------------------------------------------

def _metrics_on(tmp_path, **cfg):
    path = str(tmp_path / "step.jsonl")
    C.initialize(C.Configuration(metrics_path=path, **cfg))
    return path


def fallback_count(reason):
    return obs.registry().counter(health.FALLBACK_COUNTER, site="step",
                                  reason=reason).snapshot()["value"]


def step_count(impl):
    return obs.registry().counter("dlaf_step_kernel_total",
                                  impl=impl).snapshot()["value"]


def test_unsupported_dtype_counted(tmp_path):
    """Explicit step_impl="fused" with f64 input: the composed-chain
    landing is a COUNTED degradation; result stays correct."""
    _metrics_on(tmp_path, step_impl="fused")
    a = hpd(32, dtype=np.float64, seed=6)
    before = fallback_count("unsupported_dtype")
    out = _factor("L", a, 8).to_numpy()
    assert fallback_count("unsupported_dtype") >= before + 1
    np.testing.assert_allclose(np.tril(out), sla.cholesky(a, lower=True),
                               atol=1e-10 * 32)


def test_vmem_budget_counted(tmp_path):
    """Explicit step_impl="fused" over a starved step_vmem_limit: the
    budget overflow is a COUNTED degradation (reason="vmem_budget") and
    the factorization lands on the composed chain, still correct."""
    _metrics_on(tmp_path, step_impl="fused", step_vmem_limit=1024)
    a = hpd(32, seed=7)
    before = fallback_count("vmem_budget")
    out = _factor("L", a, 8).to_numpy()
    assert fallback_count("vmem_budget") >= before + 1
    np.testing.assert_allclose(np.tril(out),
                               sla.cholesky(a, lower=True), atol=1e-4)


def test_auto_policy_uncounted(tmp_path):
    """auto off-TPU resolves xla by POLICY — no fallback counted."""
    _metrics_on(tmp_path, step_impl="auto")
    before = fallback_count("unsupported_dtype")
    _factor("L", hpd(16, seed=7), 8)
    assert fallback_count("unsupported_dtype") == before


def test_disable_route_counted(tmp_path):
    """inject.disable_route("pallas") forces the fused step off: counted
    at site="step", factor still correct via the composed chain."""
    from dlaf_tpu.health import inject

    _metrics_on(tmp_path, step_impl="fused")
    a = hpd(32, seed=8)
    before = fallback_count("injected_off")
    with inject.disable_route("pallas"):
        out = _factor("L", a, 8).to_numpy()
    assert fallback_count("injected_off") >= before + 1
    np.testing.assert_allclose(np.tril(out),
                               sla.cholesky(a, lower=True), atol=1e-4)


def test_disable_route_strict_raises(tmp_path):
    from dlaf_tpu.health import inject
    from dlaf_tpu.health.errors import DegradationError

    _metrics_on(tmp_path, step_impl="fused", strict=True)
    with inject.disable_route("pallas"):
        with pytest.raises(DegradationError):
            _factor("L", hpd(16, seed=9), 8)


def test_step_kernel_counter(tmp_path, devices8):
    """Trace-time dlaf_step_kernel_total{impl}: one count per emitted
    strip-bearing step — nt-1 = 3 for n=32 nb=8 on the local unrolled
    and dist unrolled builders, under the impl the route resolved."""
    n, nb = 32, 8
    a = hpd(n, seed=10)
    for grid in (None, Grid(2, 2)):
        _metrics_on(tmp_path, step_impl="fused")
        base = step_count("fused")
        _factor("L", a, nb, grid=grid)
        assert step_count("fused") - base == 3, grid
        _metrics_on(tmp_path, step_impl="xla")
        base_x = step_count("xla")
        _factor("U", a, nb, grid=grid)
        assert step_count("xla") - base_x == 3, grid


# ---------------------------------------------------------------------------
# jaxpr pins (acceptance criteria)
# ---------------------------------------------------------------------------

def _iter_pallas(eqn):
    if eqn.primitive.name == "pallas_call":
        yield eqn
    for _, sub in depgraph.subjaxprs(eqn):
        for e in sub.eqns:
            yield from _iter_pallas(e)


def test_one_pallas_call_per_step(devices8):
    """jaxpr pin: the fused-step dist program holds exactly ONE
    pallas_call per strip-bearing step (nt-1) — the panel potrf and
    strip solve fused into one kernel where the fused-panel route
    needed two — plus the final step's standalone potrf when the panel
    route is also fused (2*nt-1 -> nt)."""
    from dlaf_tpu.algorithms.cholesky import _build_dist_cholesky

    C.initialize()
    grid = Grid(2, 2)
    mat = Matrix.from_global(hpd(24), TileElementSize(4, 4), grid=grid)
    nt = 6

    def count(panel_fused, step_fused):
        fn = _build_dist_cholesky(mat.dist, grid.mesh, "L", False, True,
                                  panel_fused=panel_fused,
                                  step_fused=step_fused)
        eqns = depgraph.shard_map_body(fn, mat.storage)
        return sum(1 for e in eqns for _ in _iter_pallas(e))

    assert count(panel_fused=False, step_fused=True) == nt - 1
    assert count(panel_fused=True, step_fused=True) == nt
    assert count(panel_fused=True, step_fused=False) == 2 * nt - 1


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_comm_overlap_pin_under_fused_step(uplo, devices8):
    """The PR-4 lookahead independence pin holds with step_impl=fused:
    step k+1's transposed-panel all_gather is emitted before, and is
    independent of, step k's bulk product."""
    from dlaf_tpu.algorithms.cholesky import _build_dist_cholesky

    C.initialize()
    grid = Grid(2, 2)
    mat = Matrix.from_global(hpd(24), TileElementSize(4, 4), grid=grid)
    fn = _build_dist_cholesky(mat.dist, grid.mesh, uplo, False, True,
                              lookahead=True, comm_la=True,
                              step_fused=True)
    eqns = depgraph.shard_map_body(fn, mat.storage)
    ag = depgraph.positions(eqns, "all_gather")
    bulk = depgraph.positions(eqns, depgraph.is_bulk_dot)
    assert len(ag) >= 2 and bulk
    assert ag[1] < bulk[0], (ag, bulk)
    assert not depgraph.depends_on(eqns, ag[1], depgraph.is_bulk_dot)


# ---------------------------------------------------------------------------
# the committed critpath fixture pair (pre/post, ISSUE 19)
# ---------------------------------------------------------------------------

def test_critpath_fixture_pair_gap_shrinks():
    """The committed fixture pair (tests/fixtures/critpath_prestep/ =
    composed-op step route, tests/fixtures/critpath/ = fused step route;
    same n/nb/grid/f32, same documented 2 ms injection before
    cholesky.step002 — scripts/refresh_devtrace_fixture.py) carries the
    step-gap claim hermetically: each leg's artifact pins its route via
    ``dlaf_step_kernel_total{impl}``, and the fused leg's residual
    boundary gap at the injected step is SMALLER — the one-kernel step
    spans the boundary and absorbs more of the stall."""
    from dlaf_tpu.obs import critpath
    from dlaf_tpu.obs.aggregate import merge_artifacts
    from dlaf_tpu.obs.devtrace import load_trace

    here = os.path.dirname(os.path.abspath(__file__))
    gaps = {}
    for name, impl in (("critpath_prestep", "xla"), ("critpath", "fused")):
        fixdir = os.path.join(here, "fixtures", name)
        records = merge_artifacts([os.path.join(fixdir, "merged.jsonl")])
        counts = {}
        for r in records:
            if r.get("type") == "metrics":
                for m in r["metrics"]:
                    if m["name"] == "dlaf_step_kernel_total":
                        counts[m["labels"]["impl"]] = \
                            counts.get(m["labels"]["impl"], 0) + m["value"]
        # route pin: ONLY the leg's own impl counted, 3 strip-bearing
        # steps x 2 participating artifacts
        assert counts == {impl: 6.0}, (name, counts)
        report = critpath.attribute(
            load_trace(os.path.join(fixdir, "trace.json.gz")), records)
        prog = report["programs"]["cholesky"]
        assert prog["n_steps"] == 4, (name, prog["n_steps"])
        step_gaps = [s.get("gap_after_s", 0.0) for s in prog["steps"]
                     if not s.get("empty")]
        # the injected stall surfaces at the step002 boundary and ONLY
        # there on both legs (same spec -> the pair isolates the route)
        assert max(step_gaps) == step_gaps[1] > 0, (name, step_gaps)
        gaps[name] = step_gaps[1]
    assert gaps["critpath"] < gaps["critpath_prestep"], gaps
