"""Tests for the grid and collective verbs on the 8-device CPU mesh.

Mirrors the reference's ``test/unit/communication/`` suite (bcast / reduce /
all_reduce / p2p at several grid shapes and both rank orderings,
``grids_6_ranks.h``) using shard_map over virtual devices.
"""


import numpy as np
import pytest

import jax
import jax.numpy as jnp
from dlaf_tpu._compat import shard_map
from jax.sharding import PartitionSpec as P

from dlaf_tpu.comm import collectives as cc
from dlaf_tpu.comm.grid import Grid


def _shmap(grid, f, in_specs, out_specs):
    return shard_map(f, mesh=grid.mesh, in_specs=in_specs, out_specs=out_specs,
                     check_vma=False)


@pytest.mark.parametrize("rows,cols", [(2, 4), (4, 2), (2, 2), (1, 8), (8, 1)])
def test_grid_shapes(rows, cols, devices8):
    g = Grid(rows, cols)
    assert (g.size.row, g.size.col) == (rows, cols)
    assert g.num_devices == rows * cols


def test_grid_orderings(devices8):
    g_rm = Grid(2, 4, ordering="row-major")
    g_cm = Grid(2, 4, ordering="col-major")
    devs = jax.devices()
    assert g_rm.mesh.devices[0, 1] == devs[1]
    assert g_cm.mesh.devices[0, 1] == devs[2]
    assert g_cm.mesh.devices[1, 0] == devs[1]


@pytest.mark.parametrize("axis,src", [("row", 0), ("row", 1), ("col", 2)])
def test_bcast(axis, src, devices8):
    g = Grid(2, 4)
    x = jnp.arange(8, dtype=jnp.float64).reshape(2, 4) + 1.0

    def f(x):
        blk = x.reshape(())  # local (1,1) block -> scalar
        return cc.bcast(blk, axis, src).reshape(1, 1)

    out = _shmap(g, f, P("row", "col"), P("row", "col"))(x)
    out = np.asarray(out)
    if axis == "row":
        expect = np.tile(np.asarray(x)[src: src + 1, :], (2, 1))
    else:
        expect = np.tile(np.asarray(x)[:, src: src + 1], (1, 4))
    np.testing.assert_array_equal(out, expect)


def test_bcast_complex(devices8):
    g = Grid(2, 4)
    x = (jnp.arange(8) + 1j * jnp.arange(8)).reshape(2, 4).astype(jnp.complex128)

    def f(x):
        return cc.bcast(x.reshape(()), "col", 1).reshape(1, 1)

    out = np.asarray(_shmap(g, f, P("row", "col"), P("row", "col"))(x))
    expect = np.tile(np.asarray(x)[:, 1:2], (1, 4))
    np.testing.assert_array_equal(out, expect)


@pytest.mark.parametrize("op,red", [("sum", np.sum), ("max", np.max), ("min", np.min)])
def test_all_reduce(op, red, devices8):
    g = Grid(2, 4)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 4)))

    def f(x):
        return cc.all_reduce(x.reshape(()), "col", op).reshape(1, 1)

    out = np.asarray(_shmap(g, f, P("row", "col"), P("row", "col"))(x))
    expect = np.tile(red(np.asarray(x), axis=1, keepdims=True), (1, 4))
    np.testing.assert_allclose(out, expect, rtol=1e-14)


def test_reduce_matches_allreduce_on_root(devices8):
    g = Grid(2, 4)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 4)))

    def f(x):
        return cc.reduce(x.reshape(()), "row", root=1).reshape(1, 1)

    out = np.asarray(_shmap(g, f, P("row", "col"), P("row", "col"))(x))
    np.testing.assert_allclose(out[1], np.asarray(x).sum(axis=0), rtol=1e-14)


def test_send_recv(devices8):
    g = Grid(2, 4)
    x = jnp.arange(8, dtype=jnp.float64).reshape(2, 4)

    def f(x):
        return cc.send_recv(x.reshape(()), "col", src=0, dst=3).reshape(1, 1)

    out = np.asarray(_shmap(g, f, P("row", "col"), P("row", "col"))(x))
    # dst column 3 received column 0's values; others zero
    np.testing.assert_array_equal(out[:, 3], np.asarray(x)[:, 0])
    assert np.all(out[:, :3] == 0)


def test_all_gather_panel(devices8):
    g = Grid(2, 4)
    x = jnp.arange(32, dtype=jnp.float64).reshape(8, 4)

    def f(x):  # local (4, 1) column chunk; gather along 'col' -> full row block
        return cc.all_gather(x, "col", tiled=True, concat_axis=1)

    out = _shmap(g, f, P("row", "col"), P("row", None))(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_this_rank_axis_size(devices8):
    g = Grid(2, 4)

    def f():
        r = cc.this_rank("row") * 10 + cc.this_rank("col")
        n = cc.axis_size("row") * 100 + cc.axis_size("col")
        return (r + n).reshape(1, 1)

    out = np.asarray(_shmap(g, f, (), P("row", "col"))())
    expect = np.array([[204, 205, 206, 207], [214, 215, 216, 217]])
    np.testing.assert_array_equal(out, expect)


# -- multihost glue (single-process testable surface) ------------------------

def test_multihost_grid_shapes_and_axes(devices8):
    from dlaf_tpu.comm.multihost import multihost_grid, process_info, slice_groups
    import jax

    g = multihost_grid()
    assert g.num_devices == 8
    assert g.size.row * g.size.col == 8
    assert set(g.mesh.axis_names) == {"row", "col"}
    g2 = multihost_grid(2, 4)
    assert (g2.size.row, g2.size.col) == (2, 4)
    pi, pc = process_info()
    assert pi == 0 and pc == 1
    # all virtual CPU devices sit in one ICI island
    assert len(slice_groups(jax.devices())) == 1


def test_multihost_grid_runs_algorithms(devices8):
    import numpy as np
    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.comm.multihost import multihost_grid
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix

    rng = np.random.default_rng(3)
    x = rng.standard_normal((24, 24))
    a = x @ x.T + 24 * np.eye(24)
    mat = Matrix.from_global(a, TileElementSize(4, 4), grid=multihost_grid())
    out = cholesky("L", mat)
    f = np.tril(out.to_numpy())
    assert np.linalg.norm(f @ f.T - a) / np.linalg.norm(a) < 1e-13


def test_initialize_multihost_single_process_noop():
    from dlaf_tpu.comm.multihost import initialize_multihost

    initialize_multihost()  # must not raise or disturb the backend


# -- blocking sync tier (reference communication/sync/*.h) --------------------


def test_sync_gather_matches_to_numpy(devices8):
    from dlaf_tpu.comm import sync as cs
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix

    rng = np.random.default_rng(7)
    a = rng.standard_normal((20, 12))
    mat = Matrix.from_global(a, TileElementSize(4, 4), grid=Grid(2, 4))
    np.testing.assert_array_equal(cs.gather(mat), a)
    # to_numpy IS the sync tier (the reference's tests go through sync:: too)
    np.testing.assert_array_equal(mat.to_numpy(), a)


def test_sync_gather_shards_covers_every_device(devices8):
    from dlaf_tpu.comm import sync as cs

    g = Grid(2, 4)
    x = jax.device_put(np.arange(16.0).reshape(2, 4, 2),
                       g.tile_sharding())
    shards = cs.gather_shards(x)
    assert len(shards) == 8
    assert sum(s.size for s in shards) == x.size
    assert cs.gather_shards(np.ones(3))[0].shape == (3,)


def test_sync_reduce_ops(devices8):
    from dlaf_tpu.comm import sync as cs

    parts = [np.array([1.0, -2.0]), np.array([3.0, 5.0])]
    np.testing.assert_array_equal(cs.all_reduce(parts, "sum"), [4.0, 3.0])
    np.testing.assert_array_equal(cs.all_reduce(parts, "max"), [3.0, 5.0])
    np.testing.assert_array_equal(cs.all_reduce(parts, "min"), [1.0, -2.0])
    # root is a parity argument: the host plays every rank
    np.testing.assert_array_equal(cs.reduce(parts, root=1, op="sum"), [4.0, 3.0])
    with pytest.raises(ValueError):
        cs.all_reduce(parts, "xor")


def test_sync_barrier_is_hard_fence():
    from dlaf_tpu.comm import sync as cs
    from dlaf_tpu.common.sync import hard_fence

    assert cs.barrier is hard_fence


@pytest.mark.parametrize("rows,cols,axis,src", [
    (2, 4, "col", 0), (2, 4, "col", 2), (1, 8, "col", 3), (8, 1, "row", 5),
    (2, 3, "col", 1),  # non-power-of-2 axis (last doubling round truncated)
])
def test_bcast_tree_matches_psum(rows, cols, axis, src, devices8, monkeypatch):
    """bcast_impl="tree" (binomial ppermute doubling) is value-identical to
    the psum form on every axis size/source — the knob exists so the first
    multi-chip ICI access can A/B hop latency vs ring bandwidth."""
    import dlaf_tpu.config as config

    if rows * cols > 8:
        pytest.skip("needs more virtual devices")
    g = Grid(rows, cols)
    n = rows * cols
    x = jnp.arange(n, dtype=jnp.float64).reshape(rows, cols) + 1.0

    def f(x):
        return cc.bcast(x.reshape(()), axis, src).reshape(1, 1)

    ref = np.asarray(_shmap(g, f, P("row", "col"), P("row", "col"))(x))
    monkeypatch.setenv("DLAF_BCAST_IMPL", "tree")
    config.initialize()
    try:
        out = np.asarray(_shmap(g, f, P("row", "col"), P("row", "col"))(x))
    finally:
        monkeypatch.delenv("DLAF_BCAST_IMPL")
        config.initialize()
    np.testing.assert_array_equal(out, ref)


def test_bcast_tree_full_algorithm(devices8, monkeypatch):
    """A full distributed factorization under bcast_impl="tree" matches the
    psum-broadcast result bit-for-bit (same reductions, different bcast)."""
    import dlaf_tpu.config as config
    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix

    n, nb = 16, 4
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, n))
    a = x @ x.T + n * np.eye(n)
    g = Grid(2, 4)
    ref = cholesky("L", Matrix.from_global(a, TileElementSize(nb, nb),
                                           grid=g)).to_numpy()
    monkeypatch.setenv("DLAF_BCAST_IMPL", "tree")
    config.initialize()
    try:
        out = cholesky("L", Matrix.from_global(a, TileElementSize(nb, nb),
                                               grid=g)).to_numpy()
    finally:
        monkeypatch.delenv("DLAF_BCAST_IMPL")
        config.initialize()
    np.testing.assert_allclose(np.tril(out), np.tril(ref), rtol=0, atol=0)


@pytest.mark.parametrize("rows,cols", [(2, 4), (4, 2), (2, 2), (1, 8)])
@pytest.mark.parametrize("owner_r,owner_c", [(0, 0), (1, 1)])
def test_bcast2d_matches_two_hop(rows, cols, owner_r, owner_c, devices8):
    """The fused 2D diagonal broadcast (one psum over BOTH mesh axes,
    docs/comm_overlap.md) is BITWISE identical to the two-hop
    bcast(bcast(...)) it replaces — including the signed-zero flattening
    any multi-participant psum performs."""
    g = Grid(rows, cols)
    orr, occ = owner_r % rows, owner_c % cols
    vals = np.arange(rows * cols, dtype=np.float64).reshape(rows, cols) + 1.0
    vals[0, 0] = -0.0   # the masked-add edge the contract documents
    x = jnp.asarray(vals)

    def fused(x):
        return cc.bcast2d(x.reshape(()), orr, occ).reshape(1, 1)

    def two_hop(x):
        blk = x.reshape(())
        return cc.bcast(cc.bcast(blk, "row", orr), "col", occ).reshape(1, 1)

    out_f = np.asarray(_shmap(g, fused, P("row", "col"), P("row", "col"))(x))
    out_2 = np.asarray(_shmap(g, two_hop, P("row", "col"),
                              P("row", "col"))(x))
    np.testing.assert_array_equal(out_f, out_2)
    np.testing.assert_array_equal(out_f, np.full((rows, cols),
                                                 vals[orr, occ]))


def test_bcast2d_tree_impl(devices8, monkeypatch):
    """bcast_impl="tree" has no 2-axis fusion: bcast2d falls back to the
    two-hop binomial trees with identical values."""
    import dlaf_tpu.config as config

    g = Grid(2, 4)
    x = jnp.arange(8, dtype=jnp.float64).reshape(2, 4) + 1.0

    def f(x):
        return cc.bcast2d(x.reshape(()), 1, 2).reshape(1, 1)

    ref = np.asarray(_shmap(g, f, P("row", "col"), P("row", "col"))(x))
    monkeypatch.setenv("DLAF_BCAST_IMPL", "tree")
    config.initialize()
    try:
        out = np.asarray(_shmap(g, f, P("row", "col"), P("row", "col"))(x))
    finally:
        monkeypatch.delenv("DLAF_BCAST_IMPL")
        config.initialize()
    np.testing.assert_array_equal(out, ref)
    np.testing.assert_array_equal(out, np.full((2, 4), np.asarray(x)[1, 2]))


def test_bcast2d_records_per_axis_bytes(devices8, monkeypatch, tmp_path):
    """Accounting parity with the two-hop form: one bcast2d charges the
    payload once per mesh axis under kind="bcast2d" (the per-axis byte
    counters the ICI roofline reads — scripts/mfu_table.py)."""
    import dlaf_tpu.config as config
    from dlaf_tpu import obs

    monkeypatch.setenv("DLAF_METRICS_PATH", str(tmp_path / "m.jsonl"))
    config.initialize()
    try:
        g = Grid(2, 4)
        x = jnp.arange(8, dtype=jnp.float64).reshape(2, 4) + 1.0

        def f(x):
            return cc.bcast2d(x.reshape(()), 0, 0).reshape(1, 1)

        _shmap(g, f, P("row", "col"), P("row", "col"))(x)
        snap = obs.registry().snapshot()
        got = {m["labels"]["axis"]: m["value"] for m in snap
               if m["name"] == "dlaf_comm_collective_bytes_total"
               and m["labels"].get("kind") == "bcast2d"}
        assert got.get("row", 0) == 8 and got.get("col", 0) == 8, snap
    finally:
        monkeypatch.delenv("DLAF_METRICS_PATH")
        config.initialize()
        obs._reset_for_tests()


def test_bcast2d_injection_parity(devices8):
    """corrupt_collective("bcast") must still reach the diagonal-tile
    broadcast now that it is the fused bcast2d — the drill targets "a
    broadcast on the step critical path", not a specific lowering."""
    from dlaf_tpu.health import inject

    g = Grid(2, 2)
    x = jnp.ones((2, 2), dtype=jnp.float64)

    def f(x):
        return cc.bcast2d(x.reshape(()), 0, 0).reshape(1, 1)

    with inject.corrupt_collective("bcast", nth=0, seed=1):
        out = np.asarray(_shmap(g, f, P("row", "col"), P("row", "col"))(x))
    assert np.isnan(out).all(), out
    clean = np.asarray(_shmap(g, f, P("row", "col"), P("row", "col"))(x))
    np.testing.assert_array_equal(clean, np.ones((2, 2)))


def test_reduce_root_semantics(devices8):
    """reduce() defines the result ONLY on root (zeros elsewhere) — the
    reference's contract (kernels/reduce.h: only the root's output tile is
    defined); accidental non-root reads must surface, not silently work."""
    g = Grid(2, 4)
    x = jnp.arange(8, dtype=jnp.float64).reshape(2, 4) + 1.0

    def f(x):
        return cc.reduce(x.reshape(()), "col", root=2).reshape(1, 1)

    out = np.asarray(_shmap(g, f, P("row", "col"), P("row", "col"))(x))
    rowsums = np.asarray(x).sum(axis=1)
    expect = np.zeros((2, 4))
    expect[:, 2] = rowsums
    np.testing.assert_array_equal(out, expect)


def test_multihost_layout_slice_aware():
    """The ICI/DCN layout decision (pod-only in production) is a pure
    function: fake devices with slice_index exercise the multi-slice
    branches — the col axis must stay inside one slice when the slice
    size factors over it, and slice-major ordering must hold otherwise."""
    import dataclasses

    from dlaf_tpu.comm.multihost import layout_2d, slice_groups

    @dataclasses.dataclass(frozen=True)
    class FakeDev:
        id: int
        slice_index: int

    # 2 slices x 4 devices, grid 4x2: per-slice (4) % cols (2) == 0 -> the
    # hybrid helper rejects fakes, so the slice-major heuristic must place
    # each row's 2 cols inside ONE slice
    devs = [FakeDev(i, i // 4) for i in range(8)]
    assert set(map(len, slice_groups(devs).values())) == {4}
    out = layout_2d(devs, 4, 2)
    assert out.shape == (4, 2)
    for r in range(4):
        assert len({d.slice_index for d in out[r]}) == 1, \
            f"row {r} spans slices: {[d.slice_index for d in out[r]]}"

    # grid 2x4: cols (4) == per-slice -> each row IS one slice
    out2 = layout_2d(devs, 2, 4)
    for r in range(2):
        assert len({d.slice_index for d in out2[r]}) == 1

    # single-slice world: plain reshape preserves device order
    flat = [FakeDev(i, 0) for i in range(8)]
    out3 = layout_2d(flat, 2, 4)
    assert [d.id for d in out3.ravel()] == list(range(8))

    # non-factoring shape (per=4, cols=3 x rows... use 12 devices, 3 slices
    # of 4, grid 4x3: per % cols != 0 and cols % per != 0 -> device-order
    # reshape fallback, still total
    devs12 = [FakeDev(i, i // 4) for i in range(12)]
    out4 = layout_2d(devs12, 4, 3)
    assert sorted(d.id for d in out4.ravel()) == list(range(12))
