"""reduction_to_band tests
(reference: test/unit/eigensolver/test_reduction_to_band.cpp): band
structure, eigenvalue preservation (orthogonal similarity), explicit Q
reconstruction from the stored V/taus, local + distributed.
"""

import numpy as np
import pytest

from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import RankIndex2D, TileElementSize
from dlaf_tpu.eigensolver.reduction_to_band import (BandReduction, extract_band,
                                                    reduction_to_band)
from dlaf_tpu.matrix.matrix import Matrix


def herm(n, dtype, seed):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        x = x + 1j * rng.standard_normal((n, n))
    return ((x + x.conj().T) / 2).astype(dtype)


def band_dense(red: BandReduction, n):
    """Dense band matrix from the reduced result."""
    a = red.matrix.to_numpy()
    b = red.band
    out = np.zeros_like(a)
    for r in range(b + 1):
        d = np.diagonal(a, -r)
        out += np.diag(d, -r)
        if r:
            out += np.diag(d.conj(), r)
    return out


def q_from_vt(red: BandReduction, n):
    """Accumulate Q = prod_k (I - V_k T_k V_k^H) embedded at offset (k+1)nb."""
    from dlaf_tpu.tile_ops.lapack import larft
    import jax.numpy as jnp

    a = red.matrix.to_numpy()
    nb = red.band
    taus = np.asarray(red.taus)
    q = np.eye(n, dtype=a.dtype)
    nt = (n + nb - 1) // nb
    for k in range(nt - 1):
        k1 = (k + 1) * nb
        m_p = n - k1
        pw = min(nb, a.shape[1] - k * nb)
        vf = a[k1:, k * nb: k * nb + nb]
        v = np.tril(vf, -1) + np.eye(m_p, nb)
        t = np.asarray(larft(jnp.asarray(v), jnp.asarray(taus[k].astype(a.dtype))))
        qk = np.eye(n, dtype=a.dtype)
        qk[k1:, k1:] = np.eye(m_p, dtype=a.dtype) - v @ t @ v.conj().T
        q = q @ qk
    return q


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,nb", [(16, 4), (24, 8), (13, 4), (8, 8)])
def test_red2band_local(n, nb, dtype):
    a = herm(n, dtype, n)
    mat = Matrix.from_global(a, TileElementSize(nb, nb))
    red = reduction_to_band(mat)
    bd = band_dense(red, n)
    # 1) band structure: nothing outside the band
    mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) > nb
    assert np.allclose(bd[mask], 0)
    # 2) similarity: B == Q^H A Q with the accumulated Q
    q = q_from_vt(red, n)
    np.testing.assert_allclose(q @ q.conj().T, np.eye(n), atol=1e-12)
    np.testing.assert_allclose(q.conj().T @ a @ q, bd, atol=1e-10)
    # 3) eigenvalues preserved
    np.testing.assert_allclose(np.linalg.eigvalsh(bd), np.linalg.eigvalsh(a),
                               atol=1e-10)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,nb,band", [(24, 8, 4), (24, 8, 2), (32, 16, 4),
                                       (13, 4, 2)])
def test_red2band_local_band_size(n, nb, band, dtype):
    """band_size < block size (reference reduction_to_band.h:78-87; must
    divide the block size): band structure + similarity must hold at the
    NARROW bandwidth."""
    a = herm(n, dtype, n + band)
    mat = Matrix.from_global(a, TileElementSize(nb, nb))
    red = reduction_to_band(mat, band_size=band)
    assert red.band == band
    bd = band_dense(red, n)
    mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) > band
    assert np.allclose(bd[mask], 0)
    q = q_from_vt(red, n)
    np.testing.assert_allclose(q @ q.conj().T, np.eye(n), atol=1e-12)
    np.testing.assert_allclose(q.conj().T @ a @ q, bd, atol=1e-10)
    np.testing.assert_allclose(np.linalg.eigvalsh(bd), np.linalg.eigvalsh(a),
                               atol=1e-10)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("grid_shape,src", [((2, 2), (0, 0)), ((2, 4), (1, 2)),
                                            ((4, 2), (1, 1))])
@pytest.mark.parametrize("n,nb,band", [(24, 8, 4), (29, 8, 4), (32, 8, 2),
                                       (16, 16, 4)])
def test_red2band_distributed_band_size(n, nb, band, grid_shape, src, dtype,
                                        devices8):
    """Distributed reduction with band < block size (beyond-reference: its
    distributed variant requires band == block size) must match the local
    result exactly."""
    from dlaf_tpu.comm.grid import Grid
    from dlaf_tpu.common.index2d import RankIndex2D

    a = herm(n, dtype, seed=n + band)
    local = reduction_to_band(Matrix.from_global(a, TileElementSize(nb, nb)),
                              band_size=band)
    grid = Grid(*grid_shape)
    mat = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid,
                             source_rank=RankIndex2D(src[0] % grid_shape[0],
                                                     src[1] % grid_shape[1]))
    dist = reduction_to_band(mat, band_size=band)
    assert dist.band == band
    np.testing.assert_allclose(dist.matrix.to_numpy(), local.matrix.to_numpy(),
                               atol=1e-11)
    np.testing.assert_allclose(np.asarray(dist.taus), np.asarray(local.taus),
                               atol=1e-11)
    # independent correctness: band structure + eigenvalue preservation
    bd = band_dense(dist, n)
    mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) > band
    assert np.allclose(bd[mask], 0)
    np.testing.assert_allclose(np.linalg.eigvalsh(bd), np.linalg.eigvalsh(a),
                               atol=1e-10)


def test_red2band_band_size_validation():
    from dlaf_tpu.common.asserts import DlafAssertError

    a = herm(16, np.float64, 1)
    mat = Matrix.from_global(a, TileElementSize(4, 4))
    with pytest.raises(DlafAssertError, match="not divisible"):
        reduction_to_band(mat, band_size=3)  # 4 % 3 != 0


def test_extract_band_layout():
    n, nb = 16, 4
    a = herm(n, np.float64, 3)
    red = reduction_to_band(Matrix.from_global(a, TileElementSize(nb, nb)))
    band = extract_band(red)
    assert band.shape == (nb + 1, n)
    full = red.matrix.to_numpy()
    for r in range(nb + 1):
        np.testing.assert_array_equal(band[r, : n - r], np.diagonal(full, -r))


@pytest.mark.parametrize("n,nb,b", [(16, 4, 2), (13, 4, 4), (13, 4, 1)])
def test_extract_band_sub_blocksize_and_edge(n, nb, b):
    a = herm(n, np.float64, 5)
    red = reduction_to_band(Matrix.from_global(a, TileElementSize(nb, nb)),
                            band_size=b)
    band = extract_band(red)
    assert band.shape == (b + 1, n)
    full = red.matrix.to_numpy()
    for r in range(b + 1):
        np.testing.assert_array_equal(band[r, : n - r], np.diagonal(full, -r))
        assert np.all(band[r, n - r:] == 0)


def test_extract_band_never_materializes_full_matrix(monkeypatch):
    """The device band gather keeps the host transfer at O(n*band): a full
    to_numpy() inside extract_band is a regression (round-1 review item 3;
    reference copies the band tile by tile, band_to_tridiag/mc.h:91-270)."""
    n, nb = 16, 4
    a = herm(n, np.float64, 9)
    red = reduction_to_band(Matrix.from_global(a, TileElementSize(nb, nb)))
    expected = extract_band(red)
    monkeypatch.setattr(Matrix, "to_numpy", lambda self: (_ for _ in ()).throw(
        AssertionError("extract_band must not gather the full matrix")))
    band = extract_band(red)
    np.testing.assert_array_equal(band, expected)


def test_extract_band_distributed(devices8):
    n, nb = 24, 4
    a = herm(n, np.float64, 21)
    local = reduction_to_band(Matrix.from_global(a, TileElementSize(nb, nb)))
    dist = reduction_to_band(Matrix.from_global(a, TileElementSize(nb, nb),
                                                grid=Grid(2, 4)))
    np.testing.assert_allclose(extract_band(dist), extract_band(local),
                               rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("grid_shape,src", [((2, 2), (0, 0)), ((2, 4), (1, 2)),
                                            ((4, 2), (3, 0))])
@pytest.mark.parametrize("n,nb", [(16, 4), (24, 4), (13, 4)])
def test_red2band_distributed(n, nb, grid_shape, src, devices8):
    dtype = np.float64
    a = herm(n, dtype, n + grid_shape[0])
    grid = Grid(*grid_shape)
    mat = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid,
                             source_rank=RankIndex2D(src[0] % grid_shape[0],
                                                     src[1] % grid_shape[1]))
    red = reduction_to_band(mat)
    bd = band_dense(red, n)
    mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) > nb
    assert np.allclose(bd[mask], 0, atol=1e-12)
    np.testing.assert_allclose(np.linalg.eigvalsh(bd), np.linalg.eigvalsh(a),
                               atol=1e-9)


def test_red2band_distributed_matches_local(devices8):
    n, nb = 24, 4
    a = herm(n, np.float64, 77)
    local = reduction_to_band(Matrix.from_global(a, TileElementSize(nb, nb)))
    dist = reduction_to_band(Matrix.from_global(a, TileElementSize(nb, nb),
                                                grid=Grid(2, 4)))
    np.testing.assert_allclose(dist.matrix.to_numpy(), local.matrix.to_numpy(),
                               atol=1e-11)
    np.testing.assert_allclose(np.asarray(dist.taus), np.asarray(local.taus),
                               atol=1e-11)


@pytest.mark.parametrize("dtype", [np.complex128])
def test_red2band_distributed_complex(dtype, devices8):
    n, nb = 16, 4
    a = herm(n, dtype, 5)
    red = reduction_to_band(Matrix.from_global(a, TileElementSize(nb, nb),
                                               grid=Grid(2, 2)))
    bd = band_dense(red, n)
    np.testing.assert_allclose(np.linalg.eigvalsh(bd), np.linalg.eigvalsh(a),
                               atol=1e-9)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,nb,band,grid_shape,src",
                         [(24, 4, 4, (2, 4), (0, 0)),
                          (21, 4, 4, (4, 2), (1, 1)),
                          (24, 8, 4, (2, 2), (0, 1)),
                          (19, 8, 2, (2, 4), (1, 0))])
def test_red2band_distributed_scan(n, nb, band, grid_shape, src, dtype,
                                   devices8, monkeypatch):
    """dist_step_mode="scan" reduction: traced panel offsets, rolled
    full-height geqrf panels — eigenvalues must match the dense matrix on
    offset grids, ragged sizes, sub-block bands, both dtypes."""
    monkeypatch.setenv("DLAF_DIST_STEP_MODE", "scan")
    import dlaf_tpu.config as config

    config.initialize()
    try:
        a = herm(n, dtype, n + band)
        grid = Grid(*grid_shape)
        mat = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid,
                                 source_rank=RankIndex2D(
                                     src[0] % grid_shape[0],
                                     src[1] % grid_shape[1]))
        red = reduction_to_band(mat, band_size=band)
        bd = band_dense(red, n)
        mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) > band
        assert np.allclose(bd[mask], 0, atol=1e-12)
        np.testing.assert_allclose(np.linalg.eigvalsh(bd),
                                   np.linalg.eigvalsh(a), atol=1e-9)
    finally:
        monkeypatch.delenv("DLAF_DIST_STEP_MODE")
        config.initialize()


@pytest.mark.parametrize("dtype", [np.float64, np.complex128, np.float32])
@pytest.mark.parametrize("n,band", [(32, 8), (29, 8), (24, 4), (7, 8)])
def test_red2band_local_scan_matches_unrolled(n, band, dtype, monkeypatch):
    """Local scan reduction must reproduce the unrolled local result
    exactly (same reflectors: zero rows below a Householder panel leave
    geqrf unchanged), ragged sizes and n < band included."""
    from dlaf_tpu.eigensolver.reduction_to_band import (_red2band_local,
                                                        _red2band_local_scan)
    import jax.numpy as jnp

    a = herm(n, dtype, n + band)
    eps = np.finfo(np.dtype(dtype).type(0).real.dtype).eps
    ref_a, ref_t = _red2band_local(jnp.asarray(a), nb=band)
    got_a, got_t = _red2band_local_scan(jnp.asarray(a), nb=band)
    np.testing.assert_allclose(np.asarray(got_a), np.asarray(ref_a),
                               atol=100 * n * eps)
    np.testing.assert_allclose(np.asarray(got_t), np.asarray(ref_t),
                               atol=100 * eps)


def test_red2band_local_scan_via_knob(monkeypatch, devices8):
    """dist_step_mode="scan" routes the LOCAL reduction through the scan
    form via the public API (config #4's single-chip path)."""
    monkeypatch.setenv("DLAF_DIST_STEP_MODE", "scan")
    import dlaf_tpu.config as config

    config.initialize()
    try:
        n, nb, band = 24, 8, 4
        a = herm(n, np.float64, 3)
        red = reduction_to_band(
            Matrix.from_global(a, TileElementSize(nb, nb)), band_size=band)
        bd = band_dense(red, n)
        mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) > band
        assert np.allclose(bd[mask], 0, atol=1e-12)
        np.testing.assert_allclose(np.linalg.eigvalsh(bd),
                                   np.linalg.eigvalsh(a), atol=1e-9)
    finally:
        monkeypatch.delenv("DLAF_DIST_STEP_MODE")
        config.initialize()


def test_auto_step_mode_routes_to_scan(monkeypatch):
    """dist_step_mode="auto" (the default) actually selects the scan
    formulation once the traced step count crosses the platform
    threshold — integration of config.resolve_step_mode with the
    dispatcher, not just the resolver's unit test."""
    import importlib

    import dlaf_tpu.config as config
    r2b = importlib.import_module("dlaf_tpu.eigensolver.reduction_to_band")
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix

    config.initialize()
    assert config.get_configuration().dist_step_mode == "auto"
    calls = []
    real = r2b._red2band_local_scan
    monkeypatch.setattr(r2b, "_red2band_local_scan",
                        lambda *a, **k: calls.append("scan") or real(*a, **k))
    monkeypatch.setitem(config.STEP_MODE_AUTO_SCAN_AT, "cpu", 3)
    try:
        n, band = 24, 4   # 5 panel steps >= threshold 3 -> scan
        rng = np.random.default_rng(3)
        x = rng.standard_normal((n, n))
        am = Matrix.from_global((x + x.T) / 2, TileElementSize(8, 8))
        r2b.reduction_to_band(am, band_size=band)
        assert calls == ["scan"]
        calls.clear()
        am2 = Matrix.from_global((x[:8, :8] + x[:8, :8].T) / 2,
                                 TileElementSize(4, 4))
        r2b.reduction_to_band(am2, band_size=4)   # 1 step < 3 -> unrolled
        assert calls == []
    finally:
        config.initialize()


@pytest.mark.parametrize("mxu", [False, True])
@pytest.mark.parametrize("form", ["unrolled", "scan"])
def test_red2band_trail_chunk_matches_unchunked(form, mxu, monkeypatch):
    """Row-chunking the local trailing update (config
    ``red2band_trail_chunk``) reproduces the unchunked form to rounding
    error — W = A(VT) and the rank-2 update are row-independent in A, so
    the chunked gemms are bitwise-identical; the residual ~1-ulp drift
    is XLA re-fusing the small interleaved panel matmuls between the two
    program variants. Covers both routes and a non-divisible row
    count."""
    import dlaf_tpu.config as config
    import jax.numpy as jnp

    n, band = 56, 8
    a = herm(n, np.float64, seed=11)
    if mxu:
        monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
        # min_dim=8 <= band so the tiny test's gemms stay mxu-routed
        monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "8")
    config.initialize()
    from dlaf_tpu.eigensolver.reduction_to_band import (_red2band_local,
                                                        _red2band_local_scan,
                                                        _trail_chunk)

    fn = _red2band_local if form == "unrolled" else _red2band_local_scan
    try:
        ref_a, ref_t = fn(jnp.asarray(a), nb=band)
        ref_a, ref_t = np.asarray(ref_a), np.asarray(ref_t)
        monkeypatch.setenv("DLAF_RED2BAND_TRAIL_CHUNK", "16")
        config.initialize()
        assert _trail_chunk(n, band, np.float64) == 16
        got_a, got_t = fn(jnp.asarray(a), nb=band)
        eps = np.finfo(np.float64).eps
        np.testing.assert_allclose(np.asarray(got_a), ref_a,
                                   atol=100 * n * eps)
        np.testing.assert_allclose(np.asarray(got_t), ref_t,
                                   atol=100 * eps)
    finally:
        monkeypatch.delenv("DLAF_RED2BAND_TRAIL_CHUNK", raising=False)
        monkeypatch.delenv("DLAF_F64_GEMM", raising=False)
        monkeypatch.delenv("DLAF_F64_GEMM_MIN_DIM", raising=False)
        config.initialize()


def test_red2band_trail_chunk_min_dim_clamp(monkeypatch):
    """An explicit chunk width below f64_gemm_min_dim is clamped up on
    the mxu route so chunking can never flip per-gemm routes."""
    import dlaf_tpu.config as config

    monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
    monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "32")
    monkeypatch.setenv("DLAF_RED2BAND_TRAIL_CHUNK", "16")
    config.initialize()
    from dlaf_tpu.eigensolver.reduction_to_band import _trail_chunk

    try:
        assert _trail_chunk(256, 64, np.float64) == 32
        # native route (f32): no clamp needed, explicit width honored
        assert _trail_chunk(256, 64, np.float32) == 16
        # chunk >= m disables
        assert _trail_chunk(16, 8, np.float32) == 0
        # the auto path clamps too (route invariance even at pathological
        # f64_gemm_min_dim): fake a TPU backend to reach the auto branch
        import jax

        monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "5000")
        monkeypatch.setenv("DLAF_RED2BAND_TRAIL_CHUNK", "-1")
        config.initialize()
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert _trail_chunk(16384, 8192, np.float64) == 5000
    finally:
        monkeypatch.delenv("DLAF_F64_GEMM", raising=False)
        monkeypatch.delenv("DLAF_F64_GEMM_MIN_DIM", raising=False)
        monkeypatch.delenv("DLAF_RED2BAND_TRAIL_CHUNK", raising=False)
        config.initialize()
