"""Tests for layered configuration (reference: src/init.cpp:117-177 behavior)."""

import dlaf_tpu.config as C


def test_defaults():
    cfg = C.update_configuration()
    assert cfg.grid_ordering == "row-major"
    # 0 = auto: 4096 on TPU, device-disabled on CPU (round-4 sweep)
    assert cfg.secular_device_min_k == 0


def test_user_struct_layer():
    cfg = C.update_configuration(C.Configuration(secular_device_min_k=3))
    assert cfg.secular_device_min_k == 3


def test_env_overrides_user(monkeypatch):
    monkeypatch.setenv("DLAF_SECULAR_DEVICE_MIN_K", "4")
    cfg = C.update_configuration(C.Configuration(secular_device_min_k=3))
    assert cfg.secular_device_min_k == 4


def test_cli_overrides_env(monkeypatch):
    monkeypatch.setenv("DLAF_SECULAR_DEVICE_MIN_K", "4")
    cfg = C.update_configuration(C.Configuration(secular_device_min_k=3),
                                 argv=["--dlaf:secular-device-min-k=5", "ignored", "--other"])
    assert cfg.secular_device_min_k == 5


def test_cli_bool_and_dashes(monkeypatch):
    cfg = C.update_configuration(argv=["--dlaf:print-config"])
    assert cfg.print_config is True
    cfg = C.update_configuration(argv=["--dlaf:grid-ordering=col-major"])
    assert cfg.grid_ordering == "col-major"


def test_initialize_get_finalize():
    cfg = C.initialize(C.Configuration(enable_x64=True))
    assert C.get_configuration() is cfg
    C.finalize()
    assert C.get_configuration() is not cfg  # re-initialized with defaults


def test_slices_auto_default(monkeypatch):
    """f64_gemm_slices=0 (the default) resolves per platform: 7 where f64
    is the double-f32 emulation (TPU), 8 where it is native. Explicit
    values are honored verbatim (config.py / blas._oz_slices)."""
    from dlaf_tpu.tile_ops import blas

    C.initialize()
    assert C.get_configuration().f64_gemm_slices == 0
    assert blas._oz_slices() == 8  # this suite runs on the CPU backend

    import jax
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert blas._oz_slices() == 7

    monkeypatch.setenv("DLAF_F64_GEMM_SLICES", "8")
    C.initialize()
    assert blas._oz_slices() == 8  # explicit wins on any platform

    monkeypatch.setenv("DLAF_F64_GEMM_SLICES", "10")
    import pytest
    with pytest.raises(ValueError):
        C.initialize()
    monkeypatch.delenv("DLAF_F64_GEMM_SLICES")
    C.initialize()


def test_resolve_step_mode(monkeypatch):
    # auto (the default) picks per (step count, platform) from the
    # measured compile constants; explicit modes pass through untouched
    import dlaf_tpu.config as config

    config.initialize()
    try:
        assert config.get_configuration().dist_step_mode == "auto"
        assert config.resolve_step_mode(8, "cpu") == "unrolled"
        assert config.resolve_step_mode(200, "cpu") == "scan"
        assert config.resolve_step_mode(31, "tpu") == "unrolled"
        assert config.resolve_step_mode(32, "tpu") == "scan"
        monkeypatch.setenv("DLAF_DIST_STEP_MODE", "scan")
        config.initialize()
        assert config.resolve_step_mode(2, "tpu") == "scan"
        monkeypatch.setenv("DLAF_DIST_STEP_MODE", "unrolled")
        config.initialize()
        assert config.resolve_step_mode(10_000, "tpu") == "unrolled"
    finally:
        monkeypatch.delenv("DLAF_DIST_STEP_MODE", raising=False)
        config.initialize()
