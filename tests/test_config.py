"""Tests for layered configuration (reference: src/init.cpp:117-177 behavior)."""

import dlaf_tpu.config as C


def test_defaults():
    cfg = C.update_configuration()
    assert cfg.grid_ordering == "row-major"
    assert cfg.secular_device_min_k == 4096


def test_user_struct_layer():
    cfg = C.update_configuration(C.Configuration(secular_device_min_k=3))
    assert cfg.secular_device_min_k == 3


def test_env_overrides_user(monkeypatch):
    monkeypatch.setenv("DLAF_SECULAR_DEVICE_MIN_K", "4")
    cfg = C.update_configuration(C.Configuration(secular_device_min_k=3))
    assert cfg.secular_device_min_k == 4


def test_cli_overrides_env(monkeypatch):
    monkeypatch.setenv("DLAF_SECULAR_DEVICE_MIN_K", "4")
    cfg = C.update_configuration(C.Configuration(secular_device_min_k=3),
                                 argv=["--dlaf:secular-device-min-k=5", "ignored", "--other"])
    assert cfg.secular_device_min_k == 5


def test_cli_bool_and_dashes(monkeypatch):
    cfg = C.update_configuration(argv=["--dlaf:print-config"])
    assert cfg.print_config is True
    cfg = C.update_configuration(argv=["--dlaf:grid-ordering=col-major"])
    assert cfg.grid_ordering == "col-major"


def test_initialize_get_finalize():
    cfg = C.initialize(C.Configuration(enable_x64=True))
    assert C.get_configuration() is cfg
    C.finalize()
    assert C.get_configuration() is not cfg  # re-initialized with defaults
