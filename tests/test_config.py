"""Tests for layered configuration (reference: src/init.cpp:117-177 behavior)."""

import dlaf_tpu.config as C
from dlaf_tpu.obs.logging import forget_once, once_seen_keys


def test_defaults():
    cfg = C.update_configuration()
    assert cfg.grid_ordering == "row-major"
    # 0 = auto: 4096 on TPU, device-disabled on CPU (round-4 sweep)
    assert cfg.secular_device_min_k == 0


def test_user_struct_layer():
    cfg = C.update_configuration(C.Configuration(secular_device_min_k=3))
    assert cfg.secular_device_min_k == 3


def test_env_overrides_user(monkeypatch):
    monkeypatch.setenv("DLAF_SECULAR_DEVICE_MIN_K", "4")
    cfg = C.update_configuration(C.Configuration(secular_device_min_k=3))
    assert cfg.secular_device_min_k == 4


def test_cli_overrides_env(monkeypatch):
    monkeypatch.setenv("DLAF_SECULAR_DEVICE_MIN_K", "4")
    cfg = C.update_configuration(C.Configuration(secular_device_min_k=3),
                                 argv=["--dlaf:secular-device-min-k=5", "ignored", "--other"])
    assert cfg.secular_device_min_k == 5


def test_cli_bool_and_dashes(monkeypatch):
    cfg = C.update_configuration(argv=["--dlaf:print-config"])
    assert cfg.print_config is True
    cfg = C.update_configuration(argv=["--dlaf:grid-ordering=col-major"])
    assert cfg.grid_ordering == "col-major"


def test_initialize_get_finalize():
    cfg = C.initialize(C.Configuration(enable_x64=True))
    assert C.get_configuration() is cfg
    C.finalize()
    assert C.get_configuration() is not cfg  # re-initialized with defaults


def test_slices_auto_default(monkeypatch):
    """f64_gemm_slices=0 (the default) resolves per platform: 7 where f64
    is the double-f32 emulation (TPU), 8 where it is native. Explicit
    values are honored verbatim (config.py / blas._oz_slices)."""
    from dlaf_tpu.tile_ops import blas

    C.initialize()
    assert C.get_configuration().f64_gemm_slices == 0
    assert blas._oz_slices() == 8  # this suite runs on the CPU backend

    import jax
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert blas._oz_slices() == 7

    monkeypatch.setenv("DLAF_F64_GEMM_SLICES", "8")
    C.initialize()
    assert blas._oz_slices() == 8  # explicit wins on any platform

    monkeypatch.setenv("DLAF_F64_GEMM_SLICES", "10")
    import pytest
    with pytest.raises(ValueError):
        C.initialize()
    monkeypatch.delenv("DLAF_F64_GEMM_SLICES")
    C.initialize()


def test_resolve_step_mode(monkeypatch):
    # auto (the default) picks per (step count, platform) from the
    # measured compile constants; explicit modes pass through untouched
    import dlaf_tpu.config as config

    config.initialize()
    try:
        assert config.get_configuration().dist_step_mode == "auto"
        assert config.resolve_step_mode(8, "cpu") == "unrolled"
        assert config.resolve_step_mode(200, "cpu") == "scan"
        assert config.resolve_step_mode(31, "tpu") == "unrolled"
        assert config.resolve_step_mode(32, "tpu") == "scan"
        monkeypatch.setenv("DLAF_DIST_STEP_MODE", "scan")
        config.initialize()
        assert config.resolve_step_mode(2, "tpu") == "scan"
        monkeypatch.setenv("DLAF_DIST_STEP_MODE", "unrolled")
        config.initialize()
        assert config.resolve_step_mode(10_000, "tpu") == "unrolled"
    finally:
        monkeypatch.delenv("DLAF_DIST_STEP_MODE", raising=False)
        config.initialize()


def test_resolve_platform_auto(monkeypatch, capsys):
    """The shared platform-auto resolver (config.resolve_platform_auto):
    non-auto values pass through silently; "auto" picks per the process
    default backend and announces once per (knob, backend, choice)."""
    import jax

    # explicit value: passthrough, no announcement
    out = C.resolve_platform_auto(
        "native", knob="t_knob", tpu_choice="mxu", other_choice="native",
        detail="d")
    assert out == "native" and capsys.readouterr().err == ""

    for backend, expect in (("cpu", "native"), ("tpu", "mxu")):
        monkeypatch.setattr(jax, "default_backend", lambda b=backend: b)
        forget_once("config", ("t_knob", backend, expect))
        try:
            got = C.resolve_platform_auto(
                "auto", knob="t_knob", tpu_choice="mxu",
                other_choice="native", detail="why-detail")
            assert got == expect
            msg = capsys.readouterr().err
            assert f"t_knob=auto resolved to {expect!r}" in msg
            assert "why-detail" in msg
            # second resolution: same answer, announced only once
            assert C.resolve_platform_auto(
                "auto", knob="t_knob", tpu_choice="mxu",
                other_choice="native", detail="why-detail") == expect
            assert capsys.readouterr().err == ""
        finally:
            forget_once("config", ("t_knob", backend, expect))


def test_resolved_route_accessors(monkeypatch):
    """resolved_f64_gemm/resolved_f64_trsm: the bare defaults give the
    native routes off-TPU and the mxu/mixed routes on TPU; explicit knobs
    outrank auto on any backend. The announce keys these resolutions add
    are removed on exit so later announcement-capturing tests stay
    order-independent."""
    import jax

    keys = [(k, b, c) for k, b, c in
            (("f64_gemm", "cpu", "native"), ("f64_trsm", "cpu", "native"),
             ("f64_gemm", "tpu", "mxu"), ("f64_trsm", "tpu", "mixed"))]
    pre = {k for k in keys if k in once_seen_keys("config")}
    C.initialize()  # bare defaults (f64_gemm/f64_trsm = "auto")
    try:
        assert C.resolved_f64_gemm() == "native"  # suite runs on CPU
        assert C.resolved_f64_trsm() == "native"

        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert C.resolved_f64_gemm() == "mxu"
        assert C.resolved_f64_trsm() == "mixed"

        # explicit knob outranks auto even on TPU
        C.initialize(C.Configuration(f64_gemm="native",
                                     f64_trsm="native"))
        assert C.resolved_f64_gemm() == "native"
        assert C.resolved_f64_trsm() == "native"
    finally:
        for k in keys:
            if k not in pre:
                forget_once("config", k)
        C.initialize()


def test_cholesky_trailing_auto_still_validates(monkeypatch):
    """cholesky_trailing="auto" resolves before the VALID_TRAILING gate,
    so bogus explicit values still fail fast at the driver."""
    import jax.numpy as jnp
    import numpy as np
    import pytest

    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix

    m = Matrix.from_global(jnp.asarray(np.eye(8)), TileElementSize(4, 4))
    out = cholesky("L", m)  # auto default resolves (loop on CPU) and runs
    np.testing.assert_allclose(np.tril(np.asarray(out.to_numpy())),
                               np.eye(8), atol=1e-12)
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "bogus")
    C.initialize()
    try:
        with pytest.raises(Exception, match="cholesky_trailing"):
            cholesky("L", m)
    finally:
        monkeypatch.delenv("DLAF_CHOLESKY_TRAILING")
        C.initialize()


def test_cholesky_lookahead_knob(monkeypatch):
    """cholesky_lookahead: validated enum ("0"/"1"/"auto"), env-layered,
    auto resolves per backend (1 on TPU, 0 elsewhere)."""
    import jax
    import pytest

    from dlaf_tpu.obs.logging import forget_once

    assert C.Configuration().cholesky_lookahead == "auto"
    with pytest.raises(ValueError, match="cholesky_lookahead"):
        C.initialize(C.Configuration(cholesky_lookahead="yes"))
    C.initialize(C.Configuration(cholesky_lookahead="1"))
    try:
        assert C.resolved_cholesky_lookahead() is True
        monkeypatch.setenv("DLAF_CHOLESKY_LOOKAHEAD", "0")
        C.initialize()
        assert C.resolved_cholesky_lookahead() is False
        monkeypatch.delenv("DLAF_CHOLESKY_LOOKAHEAD")
        C.initialize()
        for backend, expect in (("cpu", False), ("tpu", True)):
            monkeypatch.setattr(jax, "default_backend",
                                lambda b=backend: b)
            key = ("cholesky_lookahead", backend, "1" if expect else "0")
            forget_once("config", key)
            try:
                assert C.resolved_cholesky_lookahead() is expect
            finally:
                forget_once("config", key)
    finally:
        C.initialize(C.Configuration())
