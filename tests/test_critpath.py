"""Critical-path & stall attribution tests (ISSUE 16, dlaf_tpu.obs.critpath).

Covers the HLO schedule parser (module-name pin, innermost-scope-wins
for comm-lookahead-hoisted panels, scanstep scopes), the device-event
join on hand-built synthetic timelines — where the EXACT contract can be
pinned: a serial non-overlapping timeline with equal durations (trimming
is a no-op) recovers an injected gap to the microsecond — plus boundary
gap accounting, bound classification, what-if projections, the scan
occurrence-order reconstruction, the CSE detangler, the rebase join
fallback, single-step (n <= nb) programs, the schedule/critpath/whatif
record schema + ``--require-critpath`` accept/reject legs (coverage
below the floor must be REJECTED, with the measured coverages named),
the hermetic replay of the committed ``tests/fixtures/critpath/``
fixture (which carries a documented 2 ms synthetic gap — XLA:CPU's
spin-wait collectives make real step-boundary gaps exactly zero, so the
nonzero-gap leg needs a known injection), the CLI, the depgraph-side
static step structure (lookahead pin: NO bulk_k -> panel_{k+1} edge),
and the downstream consumers: ``mfu_table.measured_bound``,
``perf_diff`` per-step category facts / ``--json`` / ``worst_step``,
and ``bench_gate.worst_step_category``.
"""

import copy
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import dlaf_tpu.config as config
from dlaf_tpu.analysis import depgraph
from dlaf_tpu.obs import critpath
from dlaf_tpu.obs.aggregate import merge_artifacts
from dlaf_tpu.obs.devtrace import load_trace
from dlaf_tpu.obs.sinks import (CRITPATH_BOUNDS, CRITPATH_COVERAGE_FLOOR,
                                WHATIF_SCENARIOS, validate_records)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
SCRIPTS = os.path.join(REPO, "scripts")
FIXTURE = os.path.join(HERE, "fixtures", "critpath")
FIXTURE_TRACE = os.path.join(FIXTURE, "trace.json.gz")
FIXTURE_JSONL = os.path.join(FIXTURE, "merged.jsonl")


# ---------------------------------------------------------------------------
# schedule extraction from optimized HLO
# ---------------------------------------------------------------------------

_HLO = """\
HloModule jit_factorize, entry_computation_layout={(f64[4,4]{1,0})->f64[4,4]{1,0}}

ENTRY main {
  %p0 = f64[4,4] parameter(0)
  %potrf.1 = f64[4,4] custom-call(%p0), op_name="jit(factorize)/cholesky.step000.panel/potrf"
  %dot.1 = f64[4,4] dot(%potrf.1, %p0), op_name="jit(factorize)/cholesky.step000.bulk/dot_general"
  %psum.1 = f64[4,4] all-reduce(%dot.1), op_name="jit(factorize)/cholesky.step000.bulk/cholesky.step001.panel/psum"
  %solve.1 = f64[4,4] triangular-solve(%p0), op_name="jit(solve)/trsm.scanstep.panel/triangular_solve"
  %bcast.1 = f64[4,4] broadcast(%p0), op_name="jit(factorize)/broadcast_in_dim"
}
"""


def test_schedule_from_hlo():
    sched = critpath.schedule_from_hlo(_HLO)
    # the module regex must stop at the word: "HloModule name," carries a
    # trailing comma that a greedy \S+ would capture
    assert sched["module"] == "jit_factorize"
    ops = sched["ops"]
    assert ops["potrf.1"] == ["cholesky", 0, "panel"]
    assert ops["dot.1"] == ["cholesky", 0, "bulk"]
    # innermost scope wins: the comm-lookahead panel chain hoisted into
    # step 0's bulk scope is attributed to step 1's panel
    assert ops["psum.1"] == ["cholesky", 1, "panel"]
    # scan bodies are traced once — index-free scope, step -1
    assert ops["solve.1"] == ["trsm", -1, "panel"]
    assert "bcast.1" not in ops            # unscoped ops are omitted


def test_schedule_record_and_schema():
    rec = critpath.schedule_record("cholesky.dist", _HLO)
    assert rec["type"] == "schedule" and rec["module"] == "jit_factorize"
    assert rec["n_ops"] == 4
    assert rec["algos"] == {"cholesky": {"steps": 2, "scan": False},
                            "trsm": {"steps": 0, "scan": True}}
    assert not validate_records([rec])
    # a program with no step scopes yields nothing to record
    assert critpath.schedule_record("x", "HloModule m\n%a = add(b, c)") is None
    # schema: a malformed ops entry is named by index
    bad = copy.deepcopy(rec)
    bad["ops"][0] = ["just-a-name"]
    assert any("ops[0]" in e for e in validate_records([bad]))


# ---------------------------------------------------------------------------
# synthetic serial timeline: the exact-arithmetic contract
# ---------------------------------------------------------------------------


def _sched(ops, algos, module="jit_chol"):
    return {"type": "schedule", "v": 1, "ts": 1.0, "site": "chol.test",
            "module": module, "n_ops": len(ops), "algos": algos,
            "ops": ops, "rank": 0}


def _span(name="chol", dur_s=1e-3, ts=2.0, **attrs):
    return {"v": 1, "type": "span", "ts": ts, "name": name, "dur_s": dur_s,
            "depth": 0, "parent": None, "attrs": attrs, "rank": 0}


def _meta_events():
    return [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 9,
         "args": {"name": "python"}},
    ]


def _dev(name, ts, dur, module="jit_chol"):
    return {"ph": "X", "pid": 1, "tid": 1, "ts": float(ts),
            "dur": float(dur), "name": name,
            "args": {"hlo_op": name, "hlo_module": module}}


def _host(name, ts, dur):
    return {"ph": "X", "pid": 9, "tid": 1, "ts": float(ts),
            "dur": float(dur), "name": name}


def _serial_setup(n_steps=3, host_window=True):
    """A serial NON-overlapping timeline with equal 100 us durations:
    panel_k [200k, 200k+100], bulk_k [200k+100, 200k+200]. Equal
    durations make the robust-window trimming a no-op, so every
    derived number is exact arithmetic."""
    ops, events = [], _meta_events()
    if host_window:
        events.append(_host("chol", 0.0, n_steps * 200.0 + 100.0))
    for k in range(n_steps):
        ops += [[f"p{k}", "chol", k, "panel"], [f"b{k}", "chol", k, "bulk"]]
        events.append(_dev(f"p{k}", 200.0 * k, 100.0))
        events.append(_dev(f"b{k}", 200.0 * k + 100.0, 100.0))
    records = [_sched(ops, {"chol": {"steps": n_steps, "scan": False}}),
               _span(flops=1e6, n=n_steps * 32, nb=32)]
    records[-1]["flops"] = 1e6
    return events, records


def test_serial_timeline_attributes_exactly():
    events, records = _serial_setup()
    report = critpath.attribute(events, records)
    assert report["join"] == "annotation"
    assert report["coverage"] == pytest.approx(1.0)
    prog = report["programs"]["chol"]
    assert prog["n_runs"] == 1 and prog["n_steps"] == 3 and not prog["scan"]
    assert prog["wall_s"] == pytest.approx(600e-6)
    assert prog["gap_total_s"] == pytest.approx(0.0, abs=1e-12)
    for s in prog["steps"]:
        assert s["wall_s"] == pytest.approx(200e-6)
        assert s["phases"]["panel"] == pytest.approx(100e-6)
        assert s["phases"]["bulk"] == pytest.approx(100e-6)
        assert s.get("gap_after_s", 0.0) == pytest.approx(0.0, abs=1e-12)
        assert s["bound"] in CRITPATH_BOUNDS
    assert prog["critical_path"] and prog["critical_path_s"] > 0
    # flops from the entry span -> measured GF/s over the run wall
    assert prog["gflops"] == pytest.approx(1e6 / 600e-6 / 1e9)
    # what-ifs: gaps_closed saves nothing here; vocabulary is complete
    wi = {w["scenario"]: w for w in prog["whatif"]}
    assert set(wi) == set(WHATIF_SCENARIOS)
    assert wi["gaps_closed"]["saved_s"] == pytest.approx(0.0, abs=1e-12)
    assert wi["panel_free"]["saved_s"] == pytest.approx(300e-6)


def test_inject_gap_recovers_exactly_on_serial_timeline():
    """On the serial timeline the measured boundary gap grows by EXACTLY
    the injected delta (no lookahead tail to absorb it) — the arithmetic
    contract behind the CI drill and the fixture's documented 2 ms."""
    events, records = _serial_setup()
    n = critpath.inject_gap(events, records, "chol", 1, 5e-3)
    assert n == 1
    prog = critpath.attribute(events, records)["programs"]["chol"]
    steps = prog["steps"]
    assert steps[0]["gap_after_s"] == pytest.approx(5e-3, rel=1e-9)
    assert steps[1]["gap_after_s"] == pytest.approx(0.0, abs=1e-12)
    assert prog["gap_total_s"] == pytest.approx(5e-3, rel=1e-9)
    # the stalled step is now gap-bound; the others untouched
    assert steps[0]["bound"] == "gap"
    assert steps[1]["wall_s"] == pytest.approx(200e-6)


def test_parse_inject():
    assert critpath.parse_inject("cholesky.step002=2.0") == \
        ("cholesky", 2, pytest.approx(2e-3))
    with pytest.raises(ValueError, match="inject-gap"):
        critpath.parse_inject("cholesky.panel=2.0")


def test_comm_bound_step_and_collectives_free_projection():
    """An exposed collective (serial: nothing overlaps it) must dominate
    its step's bound and the collectives_free projection exactly."""
    ops = [["p0", "chol", 0, "panel"], ["c0", "chol", 0, "panel"],
           ["p1", "chol", 1, "panel"]]
    events = _meta_events() + [
        _host("chol", 0.0, 500.0),
        _dev("p0", 0.0, 50.0),
        {"ph": "X", "pid": 1, "tid": 1, "ts": 50.0, "dur": 200.0,
         "name": "all-reduce.7",
         "args": {"hlo_op": "c0", "hlo_module": "jit_chol"}},
        _dev("p1", 250.0, 50.0),
    ]
    records = [_sched(ops, {"chol": {"steps": 2, "scan": False}}), _span()]
    prog = critpath.attribute(events, records)["programs"]["chol"]
    s0 = prog["steps"][0]
    assert s0["comm_s"] == pytest.approx(200e-6)
    assert s0["comm_exposed_s"] == pytest.approx(200e-6)
    assert s0["bound"] == "comm"
    wi = {w["scenario"]: w for w in prog["whatif"]}
    assert wi["collectives_free"]["saved_s"] == pytest.approx(200e-6)


def test_single_step_program_has_no_gap_keys():
    """n <= nb: one step, no boundaries — the joiner must not emit gap
    keys, and the artifact still satisfies --require-critpath."""
    events, records = _serial_setup(n_steps=1)
    report = critpath.attribute(events, records)
    prog = report["programs"]["chol"]
    assert prog["n_steps"] == 1
    (s0,) = prog["steps"]
    assert "gap_after_s" not in s0
    assert prog["gap_total_s"] == 0.0
    assert prog["critical_path"] == ["step000.panel", "step000.bulk"]
    recs = critpath.records_from_report(report, "t.json.gz")
    assert not validate_records(recs, require_critpath=True)


def test_cse_detangle_keeps_step_windows_tight():
    """An op tagged step 0 but re-executed inside step 1's window (XLA
    CSE shares fusions across steps; the shared instr keeps the FIRST
    emitter's metadata) must be re-assigned, not stretch step 0."""
    ops = [["u0", "chol", 0, "panel"], ["u1", "chol", 1, "panel"],
           ["sh", "chol", 0, "bulk"]]
    events = _meta_events() + [
        _host("chol", 0.0, 400.0),
        _dev("u0", 0.0, 100.0), _dev("u1", 200.0, 100.0),
        _dev("sh", 50.0, 10.0), _dev("sh", 250.0, 10.0),
    ]
    records = [_sched(ops, {"chol": {"steps": 2, "scan": False}}), _span()]
    prog = critpath.attribute(events, records)["programs"]["chol"]
    assert prog["steps"][0]["wall_s"] == pytest.approx(100e-6)
    assert prog["steps"][1]["wall_s"] == pytest.approx(100e-6)
    assert prog["steps"][0]["gap_after_s"] == pytest.approx(100e-6)


def test_scan_program_reconstructs_steps_from_occurrence_order():
    """A scan body is traced once (step -1 in the schedule); iterations
    are reconstructed from per-(op, device) occurrence order, with the
    iteration total inferred from the entry span's (n, nb)."""
    ops = [["sp", "chol", -1, "panel"], ["sb", "chol", -1, "bulk"]]
    events = _meta_events() + [_host("chol", 0.0, 700.0)]
    for k in range(3):
        events.append(_dev("sp", 200.0 * k, 80.0))
        events.append(_dev("sb", 200.0 * k + 80.0, 100.0))
    records = [_sched(ops, {"chol": {"steps": 0, "scan": True}}),
               _span(n=96, nb=32)]          # ceil(96/32) = 3 iterations
    prog = critpath.attribute(events, records)["programs"]["chol"]
    assert prog["scan"] and prog["n_steps"] == 3
    for s in prog["steps"]:
        assert s["phases"]["panel"] == pytest.approx(80e-6)
        assert s["phases"]["bulk"] == pytest.approx(100e-6)
    assert prog["steps"][0]["gap_after_s"] == pytest.approx(20e-6)


def test_rebase_join_without_annotation_mirrors():
    """A mirror-less trace (no host TraceAnnotation events) still joins:
    the JSONL spans are rebased onto the device-time origin."""
    events, records = _serial_setup(host_window=False)
    report = critpath.attribute(events, records)
    assert report["join"] == "rebase"
    assert report["programs"]["chol"]["n_steps"] == 3


def test_attribute_fails_loudly_without_schedule_or_devices():
    events, records = _serial_setup()
    with pytest.raises(ValueError, match="no schedule records"):
        critpath.attribute(events, [_span()])
    with pytest.raises(ValueError, match="no device events"):
        critpath.attribute(_meta_events(), records)


# ---------------------------------------------------------------------------
# record schema + --require-critpath accept/reject
# ---------------------------------------------------------------------------


def _report_records():
    events, records = _serial_setup()
    report = critpath.attribute(events, records)
    return critpath.records_from_report(report, "t.json.gz")


def test_records_validate_and_require_critpath_accepts():
    recs = _report_records()
    assert not validate_records(recs)
    assert not validate_records(recs, require_critpath=True)
    types = [r["type"] for r in recs]
    assert types.count("critpath") == 1
    assert types.count("whatif") == len(WHATIF_SCENARIOS)


def test_require_critpath_rejects_low_coverage_naming_it():
    recs = _report_records()
    (cp,) = [r for r in recs if r["type"] == "critpath"]
    cp["coverage"] = CRITPATH_COVERAGE_FLOOR - 0.01
    errors = validate_records(recs, require_critpath=True)
    # the rejection names the measured coverages (the "(got [...])" idiom)
    assert any("coverage" in e and "got" in e for e in errors)
    # but the records stay schema-valid
    assert not validate_records(recs)


def test_require_critpath_rejects_missing_whatif():
    recs = [r for r in _report_records() if r["type"] != "whatif"]
    errors = validate_records(recs, require_critpath=True)
    assert any("whatif" in e for e in errors)


def test_critpath_schema_rejects_bad_vocabulary():
    recs = _report_records()
    bad = copy.deepcopy(recs)
    (cp,) = [r for r in bad if r["type"] == "critpath"]
    cp["bound"] = "mystery"
    assert any("bound" in e for e in validate_records(bad))
    bad = copy.deepcopy(recs)
    (cp,) = [r for r in bad if r["type"] == "critpath"]
    cp["steps"][0]["bound"] = "mystery"
    assert any("bound" in e for e in validate_records(bad))
    bad = copy.deepcopy(recs)
    wi = [r for r in bad if r["type"] == "whatif"][0]
    wi["scenario"] = "magic"
    assert any("scenario" in e for e in validate_records(bad))
    # a projection that makes things SLOWER is a computation bug
    bad = copy.deepcopy(recs)
    wi = [r for r in bad if r["type"] == "whatif"][0]
    wi["projected_wall_s"] = wi["wall_s"] * 2
    assert any("projected_wall_s" in e for e in validate_records(bad))


# ---------------------------------------------------------------------------
# the committed fixture: hermetic replay (the CI leg's contract)
# ---------------------------------------------------------------------------


def test_fixture_replays_hermetically():
    """The fixture must show per-step bound classification and a NONZERO
    measured step-boundary gap: the documented 2 ms injection before
    cholesky.step002 (XLA:CPU's spin-wait collectives make organic gaps
    exactly zero), partially absorbed by lookahead overlap but well
    above noise, at the right boundary and ONLY there."""
    records = merge_artifacts([FIXTURE_JSONL])
    report = critpath.attribute(load_trace(FIXTURE_TRACE), records)
    assert report["join"] == "annotation"
    assert report["coverage"] >= CRITPATH_COVERAGE_FLOOR
    prog = report["programs"]["cholesky"]
    assert not prog["scan"] and prog["n_steps"] == 4 and prog["n_runs"] >= 2
    steps = prog["steps"]
    gap = steps[1]["gap_after_s"]          # the gap BEFORE step 2
    assert gap > 0.5e-3
    for s in steps:
        assert s["bound"] in CRITPATH_BOUNDS
        if s["step"] != 1 and "gap_after_s" in s:
            assert s["gap_after_s"] < gap
    # the critical path walks the serial panel chain (docs/lookahead.md)
    assert prog["critical_path"][:3] == [
        "step000.panel", "step000.strip", "step001.panel"]
    assert prog["gflops"] > 0
    recs = critpath.records_from_report(report, FIXTURE_TRACE)
    assert not validate_records(records + recs, require_critpath=True)


def test_fixture_gap_injection_drill_names_the_boundary():
    """Trace-level injection before step 3 must surface as that exact
    boundary's gap — the CI must-trip drill's mechanism."""
    records = merge_artifacts([FIXTURE_JSONL])
    events = load_trace(FIXTURE_TRACE)
    base = critpath.attribute(events, records)["programs"]["cholesky"]
    n = critpath.inject_gap(events, records, "cholesky", 3, 5e-3)
    assert n >= 2
    prog = critpath.attribute(events, records)["programs"]["cholesky"]
    grew = prog["steps"][2]["gap_after_s"] - \
        base["steps"][2].get("gap_after_s", 0.0)
    # lookahead tails absorb part of the delta, never most of it
    assert grew > 2.5e-3


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_critpath_cli_reports_and_validates(tmp_path):
    out = str(tmp_path / "cp.jsonl")
    r = subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.critpath", FIXTURE_TRACE,
         FIXTURE_JSONL, "-o", out], capture_output=True, text=True,
        cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert "critical path:" in r.stdout and "what-if:" in r.stdout
    v = subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.validate", out,
         "--require-critpath"], capture_output=True, text=True, cwd=REPO)
    assert v.returncode == 0, v.stderr


def test_critpath_cli_exit_codes(tmp_path):
    # usage errors -> 2
    assert subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.critpath", FIXTURE_TRACE],
        capture_output=True, cwd=REPO).returncode == 2
    assert subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.critpath", FIXTURE_TRACE,
         FIXTURE_JSONL, "--bogus"], capture_output=True,
        cwd=REPO).returncode == 2
    # an artifact without schedule records cannot join -> 1, loudly
    bare = tmp_path / "bare.jsonl"
    bare.write_text(json.dumps(_span()) + "\n")
    r = subprocess.run(
        [sys.executable, "-m", "dlaf_tpu.obs.critpath", FIXTURE_TRACE,
         str(bare)], capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1
    assert "no schedule records" in r.stderr


# ---------------------------------------------------------------------------
# depgraph: the static step DAG (the critical-path model's skeleton)
# ---------------------------------------------------------------------------


def test_step_structure_pins_lookahead_edges(devices8):
    """The traced unrolled dist Cholesky, annotated, must expose the
    per-step phase groups, and under lookahead panel k+1 must NOT depend
    on bulk k (the serial form must — stale-test guard)."""
    from dlaf_tpu.algorithms.cholesky import _build_dist_cholesky
    from dlaf_tpu.comm.grid import Grid
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix
    from dlaf_tpu.obs._state import STATE

    config.initialize()
    grid = Grid(2, 2)
    mat = Matrix.from_global(np.eye(24), TileElementSize(4, 4), grid=grid)

    def structure(lookahead):
        old = STATE.annotate
        STATE.annotate = True       # named_span scopes only emit when on
        try:
            fn = _build_dist_cholesky(mat.dist, grid.mesh, "L", False, True,
                                      lookahead=lookahead,
                                      comm_la=lookahead)
            return depgraph.step_structure(
                depgraph.shard_map_body(fn, mat.storage))
        finally:
            STATE.annotate = old

    st = structure(lookahead=True)
    assert st["algos"]["cholesky"] == {"steps": 6, "scan": False}
    assert "cholesky.step000.panel" in st["groups"]
    assert "cholesky.step000.bulk" in st["groups"]
    serial_edges = {(f"cholesky.step{k:03d}.bulk",
                     f"cholesky.step{k + 1:03d}.panel") for k in range(5)}
    assert not serial_edges & set(map(tuple, st["edges"])), \
        "pipelined panel still depends on the previous bulk product"
    st = structure(lookahead=False)
    assert serial_edges & set(map(tuple, st["edges"])), \
        "serialized form lost its bulk->panel edge — test is stale"


# ---------------------------------------------------------------------------
# downstream consumers: mfu_table, perf_diff, bench_gate
# ---------------------------------------------------------------------------


def test_mfu_table_measured_bound_from_fixture():
    sys.path.insert(0, SCRIPTS)
    import mfu_table

    mb = mfu_table.measured_bound(FIXTURE)
    assert "cholesky" in mb
    assert mb["cholesky"].startswith("comm")     # the fixture's verdict
    assert "cpu" in mb["cholesky"]               # platform-labeled, always
    text = mfu_table.render(with_ici=False, mb=mb)
    assert "measured bound" in text
    assert mb["cholesky"] in text


def test_perf_diff_extracts_step_categories():
    sys.path.insert(0, SCRIPTS)
    from perf_diff import diff, extract, worst_step

    def cp(gap):
        return {"type": "critpath", "algo": "chol", "coverage": 0.9,
                "steps": [
                    {"step": 0, "panel_s": 1e-3, "bulk_s": 2e-3,
                     "comm_exposed_s": 0.5e-3, "copy_s": 0.0,
                     "gap_after_s": gap, "bound": "bulk"},
                    {"step": 1, "empty": True},
                ]}

    facts = extract([cp(4e-3)])
    assert facts["step_cat"]["chol.step000 panel"] == pytest.approx(1e-3)
    assert facts["step_cat"]["chol.step000 comm"] == pytest.approx(0.5e-3)
    # the gap after step 0 stalls step 1's start: keyed at the boundary
    # it precedes, matching the --inject-gap spec vocabulary
    assert facts["step_cat"]["chol.step001 gap"] == pytest.approx(4e-3)
    assert not any("step001 panel" in k for k in facts["step_cat"])
    findings = diff(extract([cp(4e-3)]), extract([cp(8e-3)]), 0.25)
    ws = worst_step(findings)
    assert ws and ws["label"] == "chol.step001 gap" and ws["regression"]
    # identical artifacts -> no worse step
    assert worst_step(diff(facts, extract([cp(4e-3)]), 0.25)) is None


@pytest.fixture()
def critpath_artifact(tmp_path):
    records = merge_artifacts([FIXTURE_JSONL])
    report = critpath.attribute(load_trace(FIXTURE_TRACE), records)
    recs = critpath.records_from_report(report, FIXTURE_TRACE)
    path = str(tmp_path / "cp_enriched.jsonl")
    with open(path, "w") as f:
        for r in records + recs:
            f.write(json.dumps(r, default=str) + "\n")
    return path


def test_perf_diff_json_contract(critpath_artifact):
    r = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "perf_diff.py"),
         critpath_artifact, critpath_artifact, "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(r.stdout)
    assert {"findings", "regressions", "worst_step",
            "coverage"} <= set(data)
    assert data["regressions"] == [] and data["worst_step"] is None


def test_perf_diff_step_gap_regression_names_the_step(critpath_artifact):
    """An injected slowdown on one step-boundary gap must exit 1 with
    that exact label — the verdict bench_gate splices in."""
    r = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "perf_diff.py"),
         critpath_artifact, critpath_artifact,
         "--inject-slowdown", "cholesky.step002 gap=1.0", "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1, r.stdout + r.stderr
    data = json.loads(r.stdout)
    assert data["worst_step"]["label"] == "cholesky.step002 gap"
    # regressions are the human verdict lines, worst first
    assert any("cholesky.step002 gap" in line
               for line in data["regressions"])


def test_bench_gate_worst_step_category(critpath_artifact):
    sys.path.insert(0, SCRIPTS)
    import bench_gate

    line = bench_gate.worst_step_category([critpath_artifact])
    assert line and line.startswith("cholesky.step") and "ms" in line
    assert bench_gate.worst_step_category([]) is None
