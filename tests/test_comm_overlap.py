"""Communication look-ahead (``comm_lookahead``, docs/comm_overlap.md).

Structural jaxpr pins: for every distributed builder with the knob on,
the NEXT step's panel collective must (a) have no transitive dependency
on the current step's bulk trailing product and (b) be emitted ahead of
it in program order — exactly the dependency/order shape that lets XLA's
async collective start/done pairs run the ICI transfer concurrently with
the bulk MXU gemms. The serialized forms are pinned too, so a stale test
cannot pass vacuously. Bitwise on/off A/Bs for the families whose pins
don't live in their own test files (cholesky/trsm knob pins are in
test_cholesky.py / test_triangular.py) ride along here.

All checks run on traced jaxprs over the 8-device CPU mesh — no
compilation, no execution. The walking itself lives in
``dlaf_tpu.analysis.depgraph`` (shared with the ``graphcheck`` auditor);
this file only keeps the builder-specific predicates and assertions.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import dlaf_tpu.config as config
from dlaf_tpu.analysis import depgraph
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.matrix.matrix import Matrix


def _mat(a, nb, grid):
    return Matrix.from_global(np.asarray(a), TileElementSize(nb, nb),
                              grid=grid)


#: Equations inside the builder's shard_map body.
_inner_eqns = depgraph.shard_map_body

#: Body equations of the FIRST lax.scan among the eqns.
_scan_body_eqns = depgraph.scan_body

_closure = depgraph.closure

#: The bulk trailing product of every dist builder under test is the only
#: dot_general with a 4D (tile-pair grid) output; panel solves, strips
#: and W/M products are <= 3D (depgraph.is_bulk_dot's default).
_is_bulk_dot = depgraph.is_bulk_dot


def _ag_positions(eqns):
    return depgraph.positions(eqns, "all_gather")


def _bulk_positions(eqns):
    return depgraph.positions(eqns, _is_bulk_dot)


def _depends_on_bulk(eqns, idx):
    return depgraph.depends_on(eqns, idx, _is_bulk_dot)


# ---------------------------------------------------------------------------
# Unrolled distributed Cholesky
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("uplo", ["L", "U"])
def test_dist_cholesky_overlap(uplo, devices8):
    """comm_lookahead=1: step k+1's transposed-panel all_gather is
    independent of step k's bulk product AND emitted before it; the
    serialized program keeps the dependency (stale-test guard)."""
    from dlaf_tpu.algorithms.cholesky import _build_dist_cholesky

    config.initialize()
    grid = Grid(2, 2)
    mat = _mat(np.eye(24), 4, grid)   # nt=6

    def trace(lookahead, comm_la):
        fn = _build_dist_cholesky(mat.dist, grid.mesh, uplo, False, True,
                                  lookahead=lookahead, comm_la=comm_la)
        return _inner_eqns(fn, mat.storage)

    eqns = trace(lookahead=True, comm_la=True)
    ag, bulk = _ag_positions(eqns), _bulk_positions(eqns)
    assert len(ag) >= 2 and bulk
    # step 1's panel all_gather: hoisted ahead of step 0's bulk product
    assert ag[1] < bulk[0], (ag, bulk)
    assert not _depends_on_bulk(eqns, ag[1])

    eqns = trace(lookahead=False, comm_la=False)
    ag = _ag_positions(eqns)
    assert _depends_on_bulk(eqns, ag[1]), \
        "serialized form lost its bulk dependency — test is stale"


# ---------------------------------------------------------------------------
# Scan distributed Cholesky (overlap by construction in the la body)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("uplo", ["L", "U"])
def test_dist_cholesky_scan_overlap(uplo, devices8):
    """The pipelined scan body applies step k-1's DEFERRED bulk product
    from the carry: the bulk dot must not consume this body's panel
    collectives (they feed step k, overlapping the bulk), while the
    serial body's bulk consumes its own panel broadcast directly."""
    from dlaf_tpu.algorithms.cholesky import _build_dist_cholesky_scan

    config.initialize()
    grid = Grid(2, 2)
    mat = _mat(np.eye(24), 4, grid)   # nt=6, multi-segment telescope

    def body(lookahead):
        fn = _build_dist_cholesky_scan(mat.dist, grid.mesh, uplo,
                                       lookahead=lookahead)
        return _scan_body_eqns(_inner_eqns(fn, mat.storage))

    eqns = body(lookahead=True)
    bulk = _bulk_positions(eqns)
    assert bulk
    bulk_deps = _closure(eqns, eqns[bulk[0]].invars)
    assert not any(e.primitive.name == "all_gather" for e in bulk_deps), \
        "pipelined scan bulk consumes this body's collectives"
    # and the collectives are emitted ahead of the deferred bulk
    assert _ag_positions(eqns)[0] < bulk[0]

    eqns = body(lookahead=False)
    bulk = _bulk_positions(eqns)
    bulk_deps = _closure(eqns, eqns[bulk[0]].invars)
    assert any(e.primitive.name == "all_gather" for e in bulk_deps), \
        "serial scan body lost its panel->bulk chain — test is stale"


# ---------------------------------------------------------------------------
# Scan distributed triangular solve
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("side,uplo,op", [("L", "L", "C"), ("R", "U", "C")])
def test_dist_solve_scan_overlap(side, uplo, op, devices8):
    """comm_lookahead=1 hoists the A-panel transpose-exchange all_gather
    ahead of the deferred bulk inside the pipelined solve body; off, it
    trails the bulk. Either way it must not depend on the bulk (it reads
    only the constant A storage)."""
    from dlaf_tpu.algorithms.triangular import _build_dist_solve_scan

    config.initialize()
    grid = Grid(2, 2)
    n, nb = 24, 4
    amat = _mat(np.eye(n), nb, grid)
    bmat = _mat(np.zeros((n, 2 * nb) if side == "L" else (2 * nb, n)),
                nb, grid)

    def body(comm_la):
        fn = _build_dist_solve_scan(amat.dist, bmat.dist, grid.mesh, side,
                                    uplo, op, "N", "float64",
                                    lookahead=True, comm_la=comm_la)
        return _scan_body_eqns(_inner_eqns(
            fn, amat.storage, bmat.storage, jnp.ones((), jnp.float64)))

    eqns = body(comm_la=True)
    ag, bulk = _ag_positions(eqns), _bulk_positions(eqns)
    assert ag and bulk
    assert ag[0] < bulk[0], (ag, bulk)
    assert not _depends_on_bulk(eqns, ag[0])

    eqns = body(comm_la=False)
    ag, bulk = _ag_positions(eqns), _bulk_positions(eqns)
    assert ag[0] > bulk[0], "comm_la=0 no longer serial — test is stale"
    assert not _depends_on_bulk(eqns, ag[0])


# ---------------------------------------------------------------------------
# Unrolled distributed HEGST
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("uplo", ["L", "U"])
def test_dist_hegst_overlap(uplo, devices8):
    """comm_lookahead=1: step k+1's transposed-panel all_gathers are
    emitted ahead of step k's bulk her2k pair products and independent
    of them; the serialized sweep keeps the dependency."""
    from dlaf_tpu.algorithms.gen_to_std import _build_dist_hegst

    config.initialize()
    grid = Grid(2, 2)
    n, nb = 24, 4
    amat = _mat(np.eye(n), nb, grid)
    lmat = _mat(np.eye(n), nb, grid)

    def trace(lookahead, comm_la):
        fn = _build_dist_hegst(amat.dist, grid.mesh, uplo,
                               lookahead=lookahead, comm_la=comm_la)
        return _inner_eqns(fn, amat.storage, lmat.storage)

    eqns = trace(lookahead=True, comm_la=True)
    ag, bulk = _ag_positions(eqns), _bulk_positions(eqns)
    # 2 transposes per chain: ag[2] is the first all_gather of step 1's
    # chain; it must precede step 0's bulk her2k products
    assert len(ag) >= 4 and bulk
    assert ag[2] < bulk[0], (ag, bulk)
    assert not _depends_on_bulk(eqns, ag[2])

    eqns = trace(lookahead=False, comm_la=False)
    ag = _ag_positions(eqns)
    assert _depends_on_bulk(eqns, ag[2]), \
        "serialized hegst lost its bulk dependency — test is stale"


# ---------------------------------------------------------------------------
# Unrolled distributed reduction_to_band
# ---------------------------------------------------------------------------

def test_dist_red2band_overlap(devices8):
    """comm_lookahead=1: panel p+1's gather all_gather is emitted ahead of
    panel p's bulk rank-2 product and independent of it; serialized, the
    gather reads the post-bulk matrix and so depends on it."""
    from dlaf_tpu.eigensolver.reduction_to_band import _build_dist_red2band

    config.initialize()
    grid = Grid(2, 2)
    n, nb = 32, 8
    mat = _mat(np.eye(n), nb, grid)

    def trace(comm_la):
        fn = _build_dist_red2band(mat.dist, grid.mesh, "float64", nb,
                                  comm_la=comm_la)
        return _inner_eqns(fn, mat.storage)

    eqns = trace(comm_la=True)
    ag, bulk = _ag_positions(eqns), _bulk_positions(eqns)
    # per step: gather all_gather + X all_gather; ag[2] = panel 1's gather
    assert len(ag) >= 3 and bulk
    assert ag[2] < bulk[0], (ag, bulk)
    assert not _depends_on_bulk(eqns, ag[2])

    eqns = trace(comm_la=False)
    ag = _ag_positions(eqns)
    assert _depends_on_bulk(eqns, ag[2]), \
        "serialized red2band lost its bulk dependency — test is stale"


# ---------------------------------------------------------------------------
# Distributed bt_reduction_to_band (bt_lookahead, docs/eigensolver_perf.md)
# ---------------------------------------------------------------------------

def _bt_builders(devices8, band=4):
    from dlaf_tpu.eigensolver import back_transform as bt

    config.initialize()
    grid = Grid(2, 2)
    n, nb = 24, 4
    amat = _mat(np.eye(n), nb, grid)
    cmat = _mat(np.zeros((n, n)), nb, grid)
    npan = -(-n // band) - 1
    taus = jnp.zeros((npan, band), jnp.float64)
    return bt, grid, amat, cmat, taus, band


def test_dist_bt_r2b_overlap(devices8):
    """bt_lookahead=1: panel p+1's V sub-panel all_gather is emitted ahead
    of panel p's bulk C update and independent of it. The chain reads only
    the constant (V, taus) storage, so it is bulk-independent under EITHER
    knob — the serialized pin is therefore the emission ORDER (gather p+1
    after bulk p), the same shape test_dist_solve_scan_overlap uses for
    the hoisted solve read."""
    bt, grid, amat, cmat, taus, band = _bt_builders(devices8)

    def trace(la):
        fn = bt._build_dist_bt_r2b(amat.dist, cmat.dist, grid.mesh, band,
                                   la=la)
        return _inner_eqns(fn, amat.storage, taus, cmat.storage)

    eqns = trace(la=True)
    ag, bulk = _ag_positions(eqns), _bulk_positions(eqns)
    assert len(ag) >= 2 and bulk
    # panel p+1's gather all_gather: hoisted ahead of panel p's bulk update
    assert ag[1] < bulk[0], (ag, bulk)
    assert not _depends_on_bulk(eqns, ag[1])

    eqns = trace(la=False)
    ag, bulk = _ag_positions(eqns), _bulk_positions(eqns)
    assert ag[1] > bulk[0], "bt_lookahead=0 no longer serial — test is stale"
    assert not _depends_on_bulk(eqns, ag[1])


def test_dist_bt_r2b_scan_overlap(devices8):
    """The scan body emits its panel gather (COL bcast + ROW all_gather)
    ahead of the bulk C-update dot by construction, reading only constant
    storage — pinned for both knob values (the knob labels the structure
    there; docs/eigensolver_perf.md)."""
    bt, grid, amat, cmat, taus, band = _bt_builders(devices8)

    for la in (False, True):
        fn = bt._build_dist_bt_r2b_scan(amat.dist, cmat.dist, grid.mesh,
                                        band, la=la)
        eqns = _scan_body_eqns(_inner_eqns(fn, amat.storage, taus,
                                           cmat.storage))
        ag, bulk = _ag_positions(eqns), _bulk_positions(eqns)
        assert ag and bulk
        assert ag[0] < bulk[0], (la, ag, bulk)
        assert not _depends_on_bulk(eqns, ag[0])


@pytest.mark.parametrize("band_div", [1, 2])
def test_bt_r2b_lookahead_bitwise(band_div, devices8, monkeypatch):
    """bt_lookahead=1 must reproduce =0 bitwise — local array path AND the
    distributed builder (same collectives, same payloads, same per-cell
    application order; the hoist is a pure emission reorder)."""
    from dlaf_tpu.eigensolver.back_transform import bt_reduction_to_band
    from dlaf_tpu.eigensolver.reduction_to_band import reduction_to_band

    rng = np.random.default_rng(11)
    n, nb = 24, 4
    x = rng.standard_normal((n, n))
    a = x @ x.T + n * np.eye(n)
    c = rng.standard_normal((n, n))
    grid = Grid(2, 2)

    def run(la, dist):
        def body():
            g = grid if dist else None
            red = reduction_to_band(_mat(a, nb, grid=g) if dist
                                    else _local_mat(a, nb),
                                    band_size=nb // band_div)
            ev = _mat(c, nb, grid=grid) if dist else c
            out = bt_reduction_to_band(red, ev)
            return out.to_numpy() if dist else np.asarray(out)
        return _with_knobs(monkeypatch, body, DLAF_BT_LOOKAHEAD=la,
                           DLAF_DIST_STEP_MODE="unrolled")

    np.testing.assert_array_equal(run("1", False), run("0", False))
    np.testing.assert_array_equal(run("1", True), run("0", True))


def _local_mat(a, nb):
    from dlaf_tpu.common.index2d import TileElementSize

    return Matrix.from_global(np.asarray(a), TileElementSize(nb, nb))


def test_bt_overlap_counters(devices8, monkeypatch, tmp_path):
    """The hoisted bt chains are accounted:
    dlaf_comm_overlapped_total{algo="bt_r2b_dist"} appears for both mesh
    axes when the distributed back-transform runs with the knob on."""
    from dlaf_tpu import obs
    from dlaf_tpu.eigensolver.back_transform import bt_reduction_to_band
    from dlaf_tpu.eigensolver.reduction_to_band import reduction_to_band

    rng = np.random.default_rng(13)
    n, nb = 24, 4
    x = rng.standard_normal((n, n))
    a = x @ x.T + n * np.eye(n)
    monkeypatch.setenv("DLAF_BT_LOOKAHEAD", "1")
    monkeypatch.setenv("DLAF_DIST_STEP_MODE", "unrolled")
    monkeypatch.setenv("DLAF_METRICS_PATH", str(tmp_path / "bt.jsonl"))
    config.initialize()
    try:
        grid = Grid(2, 2)
        red = reduction_to_band(_mat(a, nb, grid))
        bt_reduction_to_band(red, _mat(rng.standard_normal((n, n)), nb,
                                       grid))
        snap = obs.registry().snapshot()
        axes = {m["labels"]["axis"]: m["value"] for m in snap
                if m["name"] == "dlaf_comm_overlapped_total"
                and m["labels"].get("algo") == "bt_r2b_dist"}
        assert axes.get("row", 0) > 0 and axes.get("col", 0) > 0, snap
    finally:
        for key in ("DLAF_BT_LOOKAHEAD", "DLAF_DIST_STEP_MODE",
                    "DLAF_METRICS_PATH"):
            monkeypatch.delenv(key)
        config.initialize()
        obs._reset_for_tests()


# ---------------------------------------------------------------------------
# Bitwise on/off A/Bs (hegst + red2band; cholesky/trsm pins live in their
# own test files) and the overlap counters
# ---------------------------------------------------------------------------

def _with_knobs(monkeypatch, fn, **knobs):
    for key, val in knobs.items():
        monkeypatch.setenv(key, val)
    config.initialize()
    try:
        return fn()
    finally:
        for key in knobs:
            monkeypatch.delenv(key, raising=False)
        config.initialize()


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_hegst_comm_bitwise(uplo, devices8, monkeypatch):
    """Distributed blocked HEGST: comm_lookahead=1 must be bitwise equal
    to =0 (same collectives, same payloads, same per-cell order)."""
    from dlaf_tpu.algorithms.gen_to_std import gen_to_std

    rng = np.random.default_rng(3)
    n, nb = 29, 4
    az = rng.standard_normal((n, n))
    az = az + az.T
    bz = rng.standard_normal((n, n))
    bz = bz @ bz.T + n * np.eye(n)
    lchol = np.linalg.cholesky(bz)
    lz = lchol if uplo == "L" else lchol.T.copy()
    grid = Grid(2, 4)

    def run(comm):
        return _with_knobs(
            monkeypatch,
            lambda: gen_to_std(uplo, _mat(az, nb, grid),
                               _mat(lz, nb, grid)).to_numpy(),
            DLAF_CHOLESKY_LOOKAHEAD="1", DLAF_COMM_LOOKAHEAD=comm,
            DLAF_HEGST_IMPL="blocked")

    np.testing.assert_array_equal(run("1"), run("0"))


@pytest.mark.parametrize("band_div", [1, 2])
def test_red2band_comm_bitwise(band_div, devices8, monkeypatch):
    """Distributed reduction_to_band: the pipelined panel gather (strip
    first, gather before the bulk rank-2 product) must reproduce the
    serial sweep bitwise — matrix AND taus."""
    from dlaf_tpu.eigensolver.reduction_to_band import reduction_to_band

    rng = np.random.default_rng(5)
    n, nb = 37, 8
    x = rng.standard_normal((n, n))
    a = x @ x.T + n * np.eye(n)
    grid = Grid(2, 2)

    def run(comm):
        def body():
            red = reduction_to_band(_mat(a, nb, grid),
                                    band_size=nb // band_div)
            return red.matrix.to_numpy(), np.asarray(red.taus)
        return _with_knobs(monkeypatch, body,
                           DLAF_COMM_LOOKAHEAD=comm,
                           DLAF_DIST_STEP_MODE="unrolled")

    m0, t0 = run("0")
    m1, t1 = run("1")
    np.testing.assert_array_equal(m1, m0)
    np.testing.assert_array_equal(t1, t0)


def test_comm_overlap_counters(devices8, monkeypatch, tmp_path):
    """The hoisted collectives are accounted:
    dlaf_comm_overlapped_total{algo,axis} appears for both mesh axes
    when a distributed factorization runs with the knob on."""
    from dlaf_tpu import obs
    from dlaf_tpu.algorithms.cholesky import cholesky

    a = np.eye(16) * 16
    monkeypatch.setenv("DLAF_CHOLESKY_LOOKAHEAD", "1")
    monkeypatch.setenv("DLAF_COMM_LOOKAHEAD", "1")
    monkeypatch.setenv("DLAF_METRICS_PATH", str(tmp_path / "m.jsonl"))
    config.initialize()
    try:
        cholesky("L", _mat(a, 4, Grid(2, 2)))
        snap = obs.registry().snapshot()
        axes = {m["labels"]["axis"]: m["value"] for m in snap
                if m["name"] == "dlaf_comm_overlapped_total"
                and m["labels"].get("algo") == "cholesky_dist"}
        assert axes.get("row", 0) > 0 and axes.get("col", 0) > 0, snap
    finally:
        for key in ("DLAF_CHOLESKY_LOOKAHEAD", "DLAF_COMM_LOOKAHEAD",
                    "DLAF_METRICS_PATH"):
            monkeypatch.delenv(key)
        config.initialize()
        obs._reset_for_tests()
