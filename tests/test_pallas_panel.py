"""Fused Pallas panel factorization (``panel_impl``, docs/pallas_panel.md).

Interpret-mode parity suite for the ``tpu_lapack`` panel shim
(tile_ops/pallas_panel.py): kernel-level fused-vs-XLA parity within the
documented ulp bounds, end-to-end route parity across dtype x uplo x
{local, 2x2 dist}, the ``potrf_info`` NaN/failure contract, the bitwise
``cholesky_lookahead``/``comm_lookahead``/``with_info`` contracts WITHIN
the fused route, the ``site="panel"`` degradation accounting (incl. the
DLAF_STRICT raise and ``inject.disable_pallas``), and the jaxpr pins the
acceptance criteria name: a fused-route panel step emits exactly ONE
``pallas_call`` for the potrf and ONE for the strip solve, and the
comm-lookahead independence pins hold under ``panel_impl="fused"``.
"""

import os

import numpy as np
import pytest
import scipy.linalg as sla

import jax
import jax.numpy as jnp

import dlaf_tpu.config as C
from dlaf_tpu import health, obs
from dlaf_tpu.analysis import depgraph
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.matrix.matrix import Matrix
from dlaf_tpu.tile_ops import blas as tb
from dlaf_tpu.tile_ops import lapack as tl
from dlaf_tpu.tile_ops import pallas_panel as ppan

#: Documented parity bounds (docs/pallas_panel.md): the fused route is a
#: different factorization order + explicit-inverse solve application,
#: both backward-stable — parity vs the XLA route is c*n*eps with c~8
#: for the well-conditioned HPD test blocks (measured ~1e-7 rel at
#: n<=64 f32), NOT bitwise.
ULP_C = 8.0


def _bound(n, dtype):
    return ULP_C * n * float(jnp.finfo(jnp.dtype(dtype)).eps)


@pytest.fixture(autouse=True)
def _reset():
    yield
    for k in ("DLAF_PANEL_IMPL", "DLAF_METRICS_PATH",
              "DLAF_CHOLESKY_LOOKAHEAD", "DLAF_COMM_LOOKAHEAD",
              "DLAF_CHOLESKY_TRAILING", "DLAF_DIST_STEP_MODE"):
        os.environ.pop(k, None)
    obs._reset_for_tests()
    C.finalize()
    C.initialize()


def hpd(n, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    return (x @ x.T + n * np.eye(n)).astype(dtype)


def kernel_count(impl, op):
    return obs.registry().counter("dlaf_panel_kernel_total", impl=impl,
                                  op=op).snapshot()["value"]


# ---------------------------------------------------------------------------
# Kernel-level parity (interpret mode)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype,rtol", [(np.float32, None),
                                        (jnp.bfloat16, 0.06)])
@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("m", [8, 24, 64])
def test_fused_potrf_parity(uplo, m, dtype, rtol):
    a = jnp.asarray(hpd(m), dtype=dtype)
    f = ppan.fused_potrf(uplo, a, interpret=True)
    assert f.dtype == a.dtype
    ref = tl.potrf(uplo, a.astype(jnp.float32))
    tol = rtol if rtol is not None else _bound(m, np.float32)
    err = float(jnp.max(jnp.abs(f.astype(jnp.float32) - ref))
                / jnp.max(jnp.abs(ref)))
    assert err < tol, (uplo, m, err, tol)


def test_fused_potrf_passthrough_triangle():
    """LAPACK storage semantics: the opposite triangle passes through."""
    a = jnp.asarray(hpd(16))
    garbage = a + jnp.triu(jnp.full((16, 16), 7.0, jnp.float32), 1)
    f = ppan.fused_potrf("L", garbage, interpret=True)
    np.testing.assert_array_equal(np.triu(np.asarray(f), 1),
                                  np.triu(np.asarray(garbage), 1))


@pytest.mark.parametrize("combo", [("R", "L", "C", "N"), ("L", "U", "C", "N"),
                                   ("L", "L", "N", "N"), ("R", "U", "N", "U"),
                                   ("L", "L", "T", "U"), ("R", "L", "T", "N")])
@pytest.mark.parametrize("batched", [False, True])
def test_fused_panel_solve_parity(combo, batched):
    side, uplo, op, diag = combo
    na = 32
    rng = np.random.default_rng(3)
    t = np.tril(rng.standard_normal((na, na))).astype(np.float32) \
        + na * np.eye(na, dtype=np.float32)
    if uplo == "U":
        t = t.T.copy()
    t = jnp.asarray(t)
    shape = (3, na, na) if batched else \
        ((40, na) if side == "R" else (na, 40))
    b = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    out = ppan.fused_panel_solve(side, uplo, op, diag, t, b,
                                 interpret=True)
    ref = tb.trsm_panel(side, uplo, op, diag, t, b)
    err = float(jnp.max(jnp.abs(out - ref)) / jnp.max(jnp.abs(ref)))
    assert err < _bound(na, np.float32), (combo, err)


def test_fused_panel_solve_alpha():
    na = 16
    t = jnp.asarray(np.eye(na, dtype=np.float32) * 2)
    b = jnp.asarray(np.ones((na, na), np.float32))
    out = ppan.fused_panel_solve("R", "L", "N", "N", t, b, alpha=4.0,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(out), 2.0, rtol=1e-6)


def test_fused_potrf_nan_on_failure():
    """A non-positive pivot NaNs the diagonal from the failing column on
    — the potrf_info prefix contract (column 3 fails here, 1-based)."""
    a = np.diag([4.0, 9.0, -1.0, 2.0, 5.0, 1.0, 1.0, 1.0]
                ).astype(np.float32)
    f = np.asarray(ppan.fused_potrf("L", jnp.asarray(a), interpret=True))
    d = np.diagonal(f)
    assert np.isfinite(d[:2]).all(), d
    assert not np.isfinite(d[2:]).any(), d
    _, info = tl.potrf_info("L", ppan.fused_potrf("L", jnp.asarray(a),
                                                  interpret=True))
    assert int(info) == 3


# ---------------------------------------------------------------------------
# End-to-end route parity + knob contracts
# ---------------------------------------------------------------------------

def _factor(uplo, a, nb, grid=None, **kw):
    return cholesky(uplo, Matrix.from_global(a, TileElementSize(nb, nb),
                                             grid=grid), **kw)


@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("grid_shape", [None, (2, 2)])
def test_cholesky_route_parity(uplo, grid_shape, devices8, monkeypatch):
    """Fused vs XLA route pinned within the documented bound across
    uplo x {local, 2x2 dist} (f32; bf16 rides its own test below — the
    CPU XLA route has no bf16 LAPACK cholesky to compare against)."""
    n, nb = 48, 8
    a = hpd(n, seed=1)
    grid = Grid(*grid_shape) if grid_shape else None
    outs = {}
    for impl in ("xla", "fused"):
        monkeypatch.setenv("DLAF_PANEL_IMPL", impl)
        C.initialize()
        outs[impl] = np.asarray(_factor(uplo, a, nb, grid=grid).storage)
    scale = np.abs(outs["xla"]).max()
    assert np.abs(outs["fused"] - outs["xla"]).max() / scale \
        < _bound(n, np.float32)


@pytest.mark.parametrize("grid_shape", [None, (2, 2)])
def test_cholesky_bf16_fused(grid_shape, devices8, monkeypatch):
    """bf16 end-to-end on the fused route (the kernels compute in f32
    and cast back) against the f32 reference factor."""
    n, nb = 48, 8
    a = hpd(n, seed=1)
    a16 = jnp.asarray(a, dtype=jnp.bfloat16)
    monkeypatch.setenv("DLAF_PANEL_IMPL", "fused")
    C.initialize()
    grid = Grid(*grid_shape) if grid_shape else None
    out = _factor("L", a16, nb, grid=grid)
    ref = sla.cholesky(np.asarray(a16, dtype=np.float32)
                       + 0.0, lower=True)
    got = np.tril(np.asarray(out.to_numpy(), dtype=np.float32))
    assert np.abs(got - ref).max() / np.abs(ref).max() < 0.06


def test_info_agrees_on_failure(devices8, monkeypatch):
    """with_info under panel_impl fused/xla: zero agrees with zero on an
    SPD input; on a non-SPD input both routes report a failing column
    inside the truly-failing tile (the exact column is backend-prefix
    dependent — tile_ops/lapack.potrf_info's documented contract)."""
    n, nb = 32, 8
    good = hpd(n, seed=2)
    bad = good.copy()
    bad[18, 18] = -1000.0        # fails inside tile 2 (cols 17..24)
    for grid in (None, Grid(2, 2)):
        infos = {}
        for impl in ("xla", "fused"):
            monkeypatch.setenv("DLAF_PANEL_IMPL", impl)
            C.initialize()
            _, i0 = _factor("L", good, nb, grid=grid, with_info=True)
            assert int(i0) == 0, impl
            _, i1 = _factor("L", bad, nb, grid=grid, with_info=True)
            infos[impl] = int(i1)
        for impl, iv in infos.items():
            assert 17 <= iv <= 24, (impl, infos)


@pytest.mark.parametrize("trailing", ["loop", "scan"])
@pytest.mark.parametrize("grid_shape", [None, (2, 2)])
def test_lookahead_bitwise_under_fused(trailing, grid_shape, devices8,
                                       monkeypatch):
    """cholesky_lookahead (and comm_lookahead, dist) stay BITWISE
    transparent on the fused route — the knobs only reorder emission of
    the same deterministic kernels."""
    n, nb = 48, 8
    a = hpd(n, seed=4)
    grid = Grid(*grid_shape) if grid_shape else None
    monkeypatch.setenv("DLAF_PANEL_IMPL", "fused")
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", trailing)
    outs = {}
    for la in ("0", "1"):
        monkeypatch.setenv("DLAF_CHOLESKY_LOOKAHEAD", la)
        monkeypatch.setenv("DLAF_COMM_LOOKAHEAD", la)
        C.initialize()
        outs[la] = np.asarray(_factor("L", a, nb, grid=grid).storage)
    assert outs["0"].tobytes() == outs["1"].tobytes()


def test_with_info_bitwise_under_fused(devices8, monkeypatch):
    """The factor is bitwise identical with with_info on or off on the
    fused route (info is a pure extra output)."""
    a = hpd(32, seed=5)
    monkeypatch.setenv("DLAF_PANEL_IMPL", "fused")
    C.initialize()
    for grid in (None, Grid(2, 2)):
        plain = np.asarray(_factor("L", a, 8, grid=grid).storage)
        f, info = _factor("L", a, 8, grid=grid, with_info=True)
        assert int(info) == 0
        assert np.asarray(f.storage).tobytes() == plain.tobytes()


# ---------------------------------------------------------------------------
# Degradation accounting (site="panel")
# ---------------------------------------------------------------------------

def _metrics_on(tmp_path, **cfg):
    path = str(tmp_path / "panel.jsonl")
    C.initialize(C.Configuration(metrics_path=path, **cfg))
    return path


def fallback_count(reason):
    return obs.registry().counter(health.FALLBACK_COUNTER, site="panel",
                                  reason=reason).snapshot()["value"]


def test_unsupported_dtype_counted(tmp_path):
    """Explicit panel_impl="fused" with f64 input: the XLA landing is a
    COUNTED degradation; result stays correct."""
    _metrics_on(tmp_path, panel_impl="fused")
    a = hpd(32, dtype=np.float64, seed=6)
    before = fallback_count("unsupported_dtype")
    out = _factor("L", a, 8).to_numpy()
    assert fallback_count("unsupported_dtype") >= before + 1
    np.testing.assert_allclose(np.tril(out), sla.cholesky(a, lower=True),
                               atol=1e-10 * 32)


def test_auto_policy_uncounted(tmp_path):
    """auto off-TPU resolves xla by POLICY — no fallback counted."""
    _metrics_on(tmp_path, panel_impl="auto")
    before = fallback_count("unsupported_dtype")
    _factor("L", hpd(16, seed=7), 8)
    assert fallback_count("unsupported_dtype") == before


def test_disable_pallas_counted(tmp_path):
    """inject.disable_pallas forces the fused route off: counted at
    site="panel", factor still correct via the XLA route."""
    from dlaf_tpu.health import inject

    _metrics_on(tmp_path, panel_impl="fused")
    a = hpd(32, seed=8)
    before = fallback_count("injected_off")
    with inject.disable_pallas():
        out = _factor("L", a, 8).to_numpy()
    assert fallback_count("injected_off") >= before + 1
    np.testing.assert_allclose(np.tril(out),
                               sla.cholesky(a, lower=True), atol=1e-4)


def test_disable_pallas_strict_raises(tmp_path):
    from dlaf_tpu.health import inject
    from dlaf_tpu.health.errors import DegradationError

    _metrics_on(tmp_path, panel_impl="fused", strict=True)
    with inject.disable_pallas():
        with pytest.raises(DegradationError):
            _factor("L", hpd(16, seed=9), 8)


def test_kernel_counters(tmp_path, devices8):
    """Trace-time dlaf_panel_kernel_total{impl,op}: the fused dist build
    counts one potrf per step and one solve per non-final step; the xla
    route counts under impl="xla"."""
    _metrics_on(tmp_path, panel_impl="fused")
    n, nb = 48, 8          # nt = 6
    a = hpd(n, seed=10)
    base_potrf = kernel_count("fused", "potrf")
    base_solve = kernel_count("fused", "solve")
    _factor("L", a, nb, grid=Grid(2, 2))
    assert kernel_count("fused", "potrf") - base_potrf == 6
    assert kernel_count("fused", "solve") - base_solve == 5
    _metrics_on(tmp_path, panel_impl="xla")
    base_x = kernel_count("xla", "potrf")
    _factor("U", a, nb, grid=Grid(2, 2))
    assert kernel_count("xla", "potrf") - base_x == 6


def test_kernel_counters_cover_mixed_route(tmp_path, monkeypatch):
    """The documented counter contract: impl="xla" covers the native AND
    mixed/ozaki XLA panel chains — the f64 ozaki trailing (mixed fused
    factor+inverse panels) must count its potrf/solve steps too."""
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "ozaki")
    _metrics_on(tmp_path)
    n, nb = 32, 8          # nt = 4
    a = hpd(n, dtype=np.float64, seed=12)
    base_p = kernel_count("xla", "potrf")
    base_s = kernel_count("xla", "solve")
    _factor("L", a, nb)
    assert kernel_count("xla", "potrf") - base_p == 4
    assert kernel_count("xla", "solve") - base_s == 3


# ---------------------------------------------------------------------------
# jaxpr pins (acceptance criteria)
# ---------------------------------------------------------------------------

def _pallas_positions(eqns):
    return depgraph.positions(eqns, "pallas_call")


def _count_pallas(jaxpr_body):
    n = 0
    for eqns in (jaxpr_body,):
        for e in eqns:
            n += sum(1 for _ in _iter_pallas(e))
    return n


def _iter_pallas(eqn):
    if eqn.primitive.name == "pallas_call":
        yield eqn
    for _, sub in depgraph.subjaxprs(eqn):
        for e in sub.eqns:
            yield from _iter_pallas(e)


def test_fused_step_emits_one_kernel_per_panel_op(devices8):
    """jaxpr pin: the fused-route dist program holds exactly ONE
    pallas_call per potrf (nt) and ONE per strip solve (nt-1) — 2*nt-1
    total — where the XLA route holds none (its panel chain is the
    cholesky/triangular_solve op pair per step)."""
    from dlaf_tpu.algorithms.cholesky import _build_dist_cholesky

    C.initialize()
    grid = Grid(2, 2)
    mat = Matrix.from_global(hpd(24), TileElementSize(4, 4), grid=grid)
    nt = 6

    def eqns(panel_fused):
        fn = _build_dist_cholesky(mat.dist, grid.mesh, "L", False, True,
                                  panel_fused=panel_fused)
        return depgraph.shard_map_body(fn, mat.storage)

    fused = eqns(True)
    total = sum(1 for e in fused for _ in _iter_pallas(e))
    assert total == 2 * nt - 1, total
    xla = eqns(False)
    assert sum(1 for e in xla for _ in _iter_pallas(e)) == 0
    assert any(depgraph.positions(xla, "cholesky")), \
        "xla route lost its cholesky op — pin is stale"


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_comm_overlap_pin_under_fused(uplo, devices8):
    """The PR-4 lookahead independence pin holds with panel_impl=fused:
    step k+1's transposed-panel all_gather is emitted before, and is
    independent of, step k's bulk product."""
    from dlaf_tpu.algorithms.cholesky import _build_dist_cholesky

    C.initialize()
    grid = Grid(2, 2)
    mat = Matrix.from_global(hpd(24), TileElementSize(4, 4), grid=grid)
    fn = _build_dist_cholesky(mat.dist, grid.mesh, uplo, False, True,
                              lookahead=True, comm_la=True,
                              panel_fused=True)
    eqns = depgraph.shard_map_body(fn, mat.storage)
    ag = depgraph.positions(eqns, "all_gather")
    bulk = depgraph.positions(eqns, depgraph.is_bulk_dot)
    assert len(ag) >= 2 and bulk
    assert ag[1] < bulk[0], (ag, bulk)
    assert not depgraph.depends_on(eqns, ag[1], depgraph.is_bulk_dot)
