"""tile_ops.qr_panel: the TPU-trustworthy panel Householder QR.

Strategy mirrors the reference's tile-op tests (``test/unit/lapack/
test_lapack_tile.cpp``): factor random panels, rebuild Q explicitly from
the stored reflectors, and check backward error + orthogonality against
the dtype's own grade; plus agreement with the LAPACK-backed ``geqrf``
primitive (this suite runs on CPU where geqrf IS LAPACK), LAPACK edge
semantics (zero-tail columns -> tau = 0), and the config wire-in
(``qr_panel`` knob routing both forms through the same call sites).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from dlaf_tpu.tile_ops.qr_panel import (householder_qr, panel_qr,
                                         rebuild_q)


@pytest.mark.parametrize("shape", [(64, 16), (33, 16), (16, 16), (257, 32)])
@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_householder_qr_backward_error(shape, dtype):
    rng = np.random.default_rng(sum(shape))
    a = rng.standard_normal(shape)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal(shape)
    a = a.astype(dtype)
    vfull, taus = householder_qr(jnp.asarray(a))
    r = np.triu(np.asarray(vfull)[: shape[1]])
    q = rebuild_q(vfull, taus)
    m, k = shape
    assert np.linalg.norm(a - q @ r) / np.linalg.norm(a) < 50 * k * 2.3e-16
    assert np.linalg.norm(np.conj(q.T) @ q - np.eye(k)) < 50 * k * 2.3e-16
    # R's diagonal is real for complex inputs (LAPACK larfg convention)
    if np.issubdtype(dtype, np.complexfloating):
        assert np.abs(np.imag(np.diagonal(r))).max() < 1e-13


@pytest.mark.parametrize("shape,dtype", [((64, 16), np.float64),
                                         ((48, 12), np.complex128),
                                         ((16, 16), np.float64)])
def test_matches_lapack_geqrf(shape, dtype):
    """Same algorithm, same sign convention as LAPACK: V and taus agree to
    roundoff (this suite's geqrf is LAPACK — conftest pins CPU)."""
    from jax._src.lax.linalg import geqrf

    rng = np.random.default_rng(7)
    a = rng.standard_normal(shape)
    if np.issubdtype(dtype, np.complexfloating):
        a = a + 1j * rng.standard_normal(shape)
    a = jnp.asarray(a.astype(dtype))
    v1, t1 = householder_qr(a)
    v2, t2 = geqrf(a)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2),
                               rtol=0, atol=1e-12)
    np.testing.assert_allclose(np.asarray(t1), np.asarray(t2),
                               rtol=0, atol=1e-13)


def test_zero_tail_column_gives_zero_tau():
    """A column with zero tail is already reduced: tau = 0, diagonal kept
    (LAPACK dlarfg semantics — red2band relies on this for its padded
    scan rows)."""
    a = np.eye(8, 4)
    a[0, 0] = 3.0
    vfull, taus = householder_qr(jnp.asarray(a))
    # column 0 tail is zero -> tau_0 = 0 and alpha kept with its sign
    assert np.asarray(taus)[0] == 0.0
    assert np.asarray(vfull)[0, 0] == 3.0
    # remaining identity columns likewise reduce with tau = 0
    assert np.all(np.asarray(taus) == 0.0)
    np.testing.assert_array_equal(np.asarray(vfull), a)


def test_all_zero_panel():
    vfull, taus = householder_qr(jnp.zeros((12, 4), jnp.float64))
    assert np.all(np.asarray(taus) == 0.0)
    assert np.all(np.asarray(vfull) == 0.0)


def test_batched_via_vectorize():
    rng = np.random.default_rng(3)
    a = rng.standard_normal((3, 32, 8))
    vb, tb = householder_qr(jnp.asarray(a))
    assert vb.shape == (3, 32, 8) and tb.shape == (3, 8)
    v0, t0 = householder_qr(jnp.asarray(a[1]))
    np.testing.assert_array_equal(np.asarray(vb)[1], np.asarray(v0))
    np.testing.assert_array_equal(np.asarray(tb)[1], np.asarray(t0))


def test_wide_panel_matches_lapack():
    """m < k (the ragged final panel of a reduction): min(m, k) reflectors
    and taus, exactly geqrf's convention."""
    from jax._src.lax.linalg import geqrf

    rng = np.random.default_rng(5)
    a = jnp.asarray(rng.standard_normal((8, 16)))
    v1, t1 = householder_qr(a)
    v2, t2 = geqrf(a)
    assert t1.shape == t2.shape == (8,)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2),
                               rtol=0, atol=1e-13)
    np.testing.assert_allclose(np.asarray(t1), np.asarray(t2),
                               rtol=0, atol=1e-13)


def test_panel_qr_routes_by_config(monkeypatch):
    """The knob actually selects the implementation: each route's output
    is bit-identical to calling that implementation directly (the
    householder sweep is deterministic, so exact equality proves the
    dispatch — a knob lookup regression cannot hide behind roundoff-level
    agreement of the two algorithms)."""
    from jax._src.lax.linalg import geqrf

    from dlaf_tpu import config

    rng = np.random.default_rng(11)
    a = jnp.asarray(rng.standard_normal((40, 8)))
    direct = {"geqrf": geqrf(a), "householder": householder_qr(a)}
    try:
        for route in ("geqrf", "householder"):
            monkeypatch.setenv("DLAF_QR_PANEL", route)
            config.initialize()
            v, t = panel_qr(a)
            np.testing.assert_array_equal(np.asarray(v),
                                          np.asarray(direct[route][0]))
            np.testing.assert_array_equal(np.asarray(t),
                                          np.asarray(direct[route][1]))
    finally:
        monkeypatch.delenv("DLAF_QR_PANEL")
        config.initialize()


def test_red2band_residual_parity_under_householder(monkeypatch):
    """End-to-end wire-in: reduction_to_band under qr_panel=householder
    matches the geqrf route's band eigenvalues to f64 grade (the exact
    check the session-4d miniapp arms run on silicon)."""
    from dlaf_tpu import config
    from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
    from dlaf_tpu.eigensolver.reduction_to_band import reduction_to_band
    from dlaf_tpu.matrix.matrix import Matrix
    from test_reduction_to_band import band_dense

    n, nb, band = 96, 32, 16

    def fn(i, j):
        return np.cos(0.001 * (i * 31 + j * 17)) \
            + np.cos(0.001 * (j * 31 + i * 17))

    ref = Matrix.from_element_fn(fn, GlobalElementSize(n, n),
                                 TileElementSize(nb, nb), dtype=np.float64)
    a = ref.to_numpy()
    w_ref = np.linalg.eigvalsh(a)
    try:
        for route in ("householder", "geqrf"):
            monkeypatch.setenv("DLAF_QR_PANEL", route)
            config.initialize()
            red = reduction_to_band(ref, band_size=band)
            w = np.linalg.eigvalsh(band_dense(red, n))
            resid = np.abs(w - w_ref).max() / np.abs(w_ref).max()
            assert resid < 100 * n * 2.3e-16, (route, resid)
    finally:
        monkeypatch.delenv("DLAF_QR_PANEL")
        config.initialize()
