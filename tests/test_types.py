"""Tests for dlaf_tpu.types (reference test: implicit via types.h usage)."""

import numpy as np
import pytest

from dlaf_tpu import types as T


def test_device_backend_mappings():
    assert T.default_device(T.Backend.MC) is T.Device.CPU
    assert T.default_device(T.Backend.TPU) is T.Device.TPU
    assert T.default_backend(T.Device.CPU) is T.Backend.MC
    assert T.default_backend(T.Device.TPU) is T.Backend.TPU


@pytest.mark.parametrize("letter,dtype", [("s", np.float32), ("d", np.float64),
                                          ("c", np.complex64), ("z", np.complex128)])
def test_type_letters(letter, dtype):
    assert T.ELEMENT_TYPES[letter] == dtype
    assert T.type_letter(dtype) == letter


def test_flop_weights():
    # reference types.h:120-131: real add=1 mul=1; complex add=2 mul=6
    assert T.total_ops(np.float64, 10, 20) == 30
    assert T.total_ops(np.complex128, 10, 20) == 2 * 10 + 6 * 20
    # cholesky model: n^3/6 adds + n^3/6 muls -> n^3/3 real
    n = 6.0
    assert T.total_ops(np.float32, n**3 / 6, n**3 / 6) == pytest.approx(n**3 / 3)


def test_base_and_complex_of():
    assert T.base_float(np.complex64) == np.float32
    assert T.base_float(np.complex128) == np.float64
    assert T.complex_of(np.float32) == np.complex64
    assert T.complex_of(np.float64) == np.complex128
    assert T.is_complex(np.complex64) and not T.is_complex(np.float64)


def test_ceil_div():
    assert T.ceil_div(0, 4) == 0
    assert T.ceil_div(1, 4) == 1
    assert T.ceil_div(4, 4) == 1
    assert T.ceil_div(5, 4) == 2
    with pytest.raises(ValueError):
        T.ceil_div(1, 0)
