"""Tests for ISSUE 15: the accuracy-steered precision autotuner
(dlaf_tpu.autotune, docs/autotune.md).

Covers: the pure decision core (escalate-on-breach, relax-after-K,
hysteresis determinism under injected probe sequences), table
persistence round-trip + loud refusal of malformed/stale/version-
mismatched tables (naming the field), the DLAF_AUTOTUNE=0 bitwise
passthrough (factor bytes identical knob on/off, local + distributed),
the closed loop end-to-end (nan_tile breach -> escalate record + gauge,
exhaustion -> flight dump + DLAF_STRICT raise), the ``autotune`` record
schema + ``--require-autotune`` validator legs, per-bucket serve routing
with the zero-steady-state-retrace pin (a route change is a NEW program,
never a retrace), the probe-cadence knob, the ozaki_impl=pallas ladder
rung (selectable by route, drill-able via inject.disable_ozaki), and the
bench-gate autotune speedup leg.
"""

import json
import math
import multiprocessing
import os
import subprocess
import sys

import numpy as np
import pytest

import dlaf_tpu.config as C
import dlaf_tpu.autotune as at
from dlaf_tpu import obs
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.matrix.matrix import Matrix
from dlaf_tpu.miniapp.generators import hpd_element_fn
from dlaf_tpu.obs.sinks import validate_records

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_AT_ENV = ("DLAF_AUTOTUNE", "DLAF_AUTOTUNE_TABLE", "DLAF_AUTOTUNE_MARGIN",
           "DLAF_AUTOTUNE_RELAX_AFTER", "DLAF_AUTOTUNE_BUDGET",
           "DLAF_AUTOTUNE_PROBE_EVERY", "DLAF_METRICS_PATH", "DLAF_LOG",
           "DLAF_STRICT", "DLAF_ACCURACY", "DLAF_PROGRAM_TELEMETRY",
           "DLAF_FLIGHT_RECORDER", "DLAF_F64_GEMM",
           "DLAF_F64_GEMM_MIN_DIM", "DLAF_OZAKI_IMPL")


@pytest.fixture(autouse=True)
def autotune_reset():
    """Every test leaves the suite with the default (steering-off)
    config, an empty process table, and the obs layer unconfigured."""
    yield
    for key in _AT_ENV:
        os.environ.pop(key, None)
    at._reset_for_tests()
    obs._reset_for_tests()
    C.finalize()
    C.initialize()


def _arm(tmp_path=None, **env):
    for k, v in env.items():
        os.environ[k] = str(v)
    if tmp_path is not None:
        os.environ["DLAF_METRICS_PATH"] = str(tmp_path / "art.jsonl")
    os.environ.setdefault("DLAF_LOG", "off")
    C.initialize()
    at._reset_for_tests()


def _records(tmp_path, rtype=None):
    obs.flush()
    path = tmp_path / "art.jsonl"
    recs = [json.loads(line) for line in open(path)]
    return [r for r in recs if rtype is None or r.get("type") == rtype]


def _hpd_matrix(n=48, nb=16, dtype=np.float64, grid=None):
    return Matrix.from_element_fn(
        hpd_element_fn(n, dtype), GlobalElementSize(n, n),
        TileElementSize(nb, nb), dtype=dtype, grid=grid)


F64 = at.LADDER_F64
KEY = at.site_key("cholesky", n=48, nb=16, dtype=np.float64,
                  platform="cpu")


def _decide_seq(ratios, *, margin=0.25, relax_after=3, budget=0,
                ladder=F64, start=None):
    """Replay a probe sequence through the PURE decision core from a
    fresh state; returns the (reason, rung) trail."""
    rung = ladder.start if start is None else start
    holds = changes = 0
    trail = []
    for ratio in ratios:
        reason, rung, holds, changes = at.decide(
            rung, holds, changes, ratio, ladder_len=len(ladder.rungs),
            margin=margin, relax_after=relax_after, budget=budget)
        trail.append((reason, rung))
    return trail


# ---------------------------------------------------------------------------
# Decision core (pure function: escalate / relax / hysteresis)
# ---------------------------------------------------------------------------

class TestDecisionCore:
    def test_escalate_on_breach_is_immediate(self):
        assert _decide_seq([3.0]) == [("escalate", F64.start + 1)]

    def test_nonfinite_probe_is_a_breach(self):
        for bad in (float("nan"), float("inf")):
            assert _decide_seq([bad]) == [("escalate", F64.start + 1)]

    def test_relax_needs_exactly_k_consecutive_comfortable(self):
        trail = _decide_seq([0.01] * 3, relax_after=3)
        assert trail == [("hold", 3), ("hold", 3), ("relax", 2)]
        # one probe short of K holds forever
        trail = _decide_seq([0.01] * 2, relax_after=3)
        assert all(reason == "hold" for reason, _ in trail)

    def test_hysteresis_band_resets_the_streak(self):
        # two comfortable, one in (margin, 1], two more comfortable:
        # the mid-band probe must restart the relax clock
        trail = _decide_seq([0.01, 0.01, 0.5, 0.01, 0.01], relax_after=3)
        assert [r for r, _ in trail] == ["hold"] * 5
        # ...and a third consecutive comfortable probe then relaxes
        trail = _decide_seq([0.01, 0.01, 0.5, 0.01, 0.01, 0.01],
                            relax_after=3)
        assert trail[-1] == ("relax", F64.start - 1)

    def test_relax_stops_at_the_floor(self):
        trail = _decide_seq([0.01] * 40, relax_after=3)
        rungs = [rung for _, rung in trail]
        assert min(rungs) == 0 and rungs[-1] == 0
        assert trail[-1][0] == "hold"

    def test_budget_limits_relaxes_not_escalations(self):
        # budget 1: one relax allowed, later comfortable streaks hold
        trail = _decide_seq([0.01] * 12, relax_after=3, budget=1)
        assert sum(r == "relax" for r, _ in trail) == 1
        # an escalation still runs with the budget exhausted
        trail = _decide_seq([0.01, 0.01, 0.01, 3.0], relax_after=3,
                            budget=1)
        assert trail[-1][0] == "escalate"

    def test_exhausted_at_the_top_rung(self):
        top = len(F64.rungs) - 1
        assert _decide_seq([5.0], start=top) == [("exhausted", top)]

    def test_breach_resets_the_comfortable_streak(self):
        trail = _decide_seq([0.01, 0.01, 3.0, 0.01, 0.01], relax_after=3)
        assert trail[2][0] == "escalate"
        assert all(r == "hold" for r, _ in trail[3:])

    def test_decision_trail_is_deterministic(self):
        seq = [0.01, 0.6, float("nan"), 0.01, 0.01, 0.01, 2.0, 0.1]
        assert _decide_seq(seq) == _decide_seq(seq)
        # and through the stateful table too: two fresh tables fed the
        # same probes produce the same entries (the drill replay pin)
        t1, t2 = at.RouteTable(), at.RouteTable()
        for table in (t1, t2):
            for ratio in seq:
                table.observe(KEY, F64, ratio, margin=0.25,
                              relax_after=3, budget=0)
        assert t1.to_json() == t2.to_json()

    def test_ladder_start_rungs_are_the_platform_defaults(self):
        # f64: the start rung pins s=7 (the TPU auto default) and
        # nothing else; f32: the start rung is the EMPTY route — the
        # knob-on/off bitwise passthrough rests on this
        assert F64.rungs[F64.start].as_dict() == {"f64_gemm_slices": 7}
        assert at.LADDER_F32.rungs[at.LADDER_F32.start].as_dict() == {}


# ---------------------------------------------------------------------------
# Table persistence: round-trip + loud refusal
# ---------------------------------------------------------------------------

def _hammer_table(path, n_saves):
    """Fork-child body of the write-rename race drill: repeatedly
    replace the table at ``path`` through the atomic save discipline.
    Touches only pure-python table code (fork-safe under a jax-hosting
    parent)."""
    table = at.RouteTable()
    for ratio in (3.0, 0.01, 0.01, 0.01):
        table.observe(KEY, F64, ratio, margin=0.25, relax_after=3,
                      budget=0)
    for _ in range(n_saves):
        table.save(path)


class TestTablePersistence:
    def _learned(self):
        table = at.RouteTable()
        for ratio in (3.0, float("nan"), 0.01, 0.01, 0.01):
            table.observe(KEY, F64, ratio, margin=0.25, relax_after=3,
                          budget=0)
        return table

    def test_roundtrip_preserves_entries(self, tmp_path):
        table = self._learned()
        path = str(tmp_path / "table.json")
        table.save(path)
        loaded = at.RouteTable()
        loaded.load(path)
        assert loaded.to_json() == table.to_json()
        # nonfinite history entries survive as nulls, not JSON NaN
        raw = open(path).read()
        assert "NaN" not in raw and "null" in raw

    def test_save_is_atomic(self, tmp_path):
        table = self._learned()
        path = str(tmp_path / "table.json")
        table.save(path)
        leftovers = [p for p in os.listdir(tmp_path) if ".tmp." in p]
        assert leftovers == []

    @pytest.mark.parametrize("mutate,field", [
        (lambda d: d.pop("version"), "version"),
        (lambda d: d.update(version=99), "version"),
        (lambda d: d.update(entries={}), "entries"),
        (lambda d: d["entries"][0].pop("rung"), "rung"),
        (lambda d: d["entries"][0].update(rung=-1), "rung"),
        (lambda d: d["entries"][0].update(rung=999), "rung"),
        (lambda d: d["entries"][0].pop("op"), "op"),
        (lambda d: d["entries"][0].update(ladder="f64:2:bogus"), "ladder"),
        (lambda d: d["entries"][0].update(dtype="int16"), "dtype"),
        (lambda d: d["entries"][0].update(history="x"), "history"),
    ])
    def test_malformed_or_stale_refuses_naming_the_field(self, mutate,
                                                         field):
        doc = self._learned().to_json()
        mutate(doc)
        with pytest.raises(ValueError, match=field):
            at.RouteTable().load_dict(doc)

    def test_unparsable_file_refuses_loudly(self, tmp_path):
        path = tmp_path / "table.json"
        path.write_text("{not json")
        with pytest.raises(ValueError, match="unparsable"):
            at.RouteTable().load(str(path))

    def test_observe_persists_when_armed(self, tmp_path):
        path = str(tmp_path / "table.json")
        table = at.RouteTable(path)
        table.observe(KEY, F64, 3.0, margin=0.25, relax_after=3, budget=0)
        on_disk = at.RouteTable()
        on_disk.load(path)
        assert on_disk.rung_of(KEY) == F64.start + 1

    def test_get_table_warm_starts_from_the_knob(self, tmp_path):
        path = str(tmp_path / "table.json")
        self._learned().save(path)
        _arm(tmp_path, DLAF_AUTOTUNE="1", DLAF_AUTOTUNE_TABLE=path)
        assert at.get_table().rung_of(KEY) is not None

    def test_get_table_refuses_a_malformed_committed_table(self, tmp_path):
        path = tmp_path / "table.json"
        path.write_text(json.dumps({"version": 42, "entries": []}))
        _arm(tmp_path, DLAF_AUTOTUNE="1", DLAF_AUTOTUNE_TABLE=str(path))
        with pytest.raises(ValueError, match="version"):
            at.get_table()

    def test_committed_repo_table_loads_clean(self):
        """The repo's warm-start table (.autotune_table.json) must stay
        loadable by this build — a ladder edit without a table refresh
        fails HERE, not in CI."""
        path = os.path.join(REPO, ".autotune_table.json")
        assert os.path.exists(path), "committed .autotune_table.json missing"
        table = at.RouteTable()
        table.load(path)
        assert table.snapshot(), "committed table has no entries"
        # the committed steady state: every entry fully relaxed (rung 0)
        # so the CI warm-start leg holds with ZERO route changes
        assert all(e["rung"] == 0 for e in table.snapshot().values())

    def test_load_retries_once_on_a_mid_replace_read(self, tmp_path,
                                                     monkeypatch):
        """A reader whose first open lands mid-replace (transient short
        read on the dying inode) must retry once and succeed — fleet
        workers warm-start from one shared committed table while the
        autotune loop may still be persisting to it."""
        from dlaf_tpu.autotune import table as table_mod
        path = str(tmp_path / "table.json")
        self._learned().save(path)
        calls = {"n": 0}
        real = table_mod.json.load

        def flaky(f, *args, **kwargs):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ValueError("Expecting value: line 1 column 1")
            return real(f, *args, **kwargs)

        monkeypatch.setattr(table_mod.json, "load", flaky)
        loaded = at.RouteTable()
        loaded.load(path)
        assert calls["n"] == 2
        assert loaded.rung_of(KEY) is not None

    def test_load_still_refuses_a_genuinely_corrupt_table(self, tmp_path):
        path = tmp_path / "table.json"
        path.write_text('{"version": 3, "entr')    # truncated for real
        with pytest.raises(ValueError, match="unparsable autotune table"):
            at.RouteTable().load(str(path))

    @pytest.mark.skipif(
        "fork" not in multiprocessing.get_all_start_methods(),
        reason="needs the fork start method")
    def test_concurrent_writers_never_corrupt_a_reader(self, tmp_path):
        """N processes hammering one table path through the atomic
        write-rename (tmp + fsync + os.replace) while a reader loads in
        a loop: every load sees a COMPLETE table (old or new, never a
        torn one), and no .tmp litter survives."""
        path = str(tmp_path / "table.json")
        self._learned().save(path)      # the reader always has a table
        ctx = multiprocessing.get_context("fork")
        procs = [ctx.Process(target=_hammer_table, args=(path, 30))
                 for _ in range(3)]
        for p in procs:
            p.start()
        reads = 0
        try:
            while any(p.is_alive() for p in procs) or reads < 20:
                loaded = at.RouteTable()
                loaded.load(path)
                assert loaded.snapshot(), "reader saw an empty table"
                reads += 1
        finally:
            for p in procs:
                p.join(timeout=60)
        assert all(p.exitcode == 0 for p in procs), \
            [p.exitcode for p in procs]
        assert reads >= 20
        leftovers = [p for p in os.listdir(tmp_path) if ".tmp." in p]
        assert leftovers == []


# ---------------------------------------------------------------------------
# Bitwise passthrough + steering integration on the entries
# ---------------------------------------------------------------------------

class TestEntrySteering:
    @pytest.mark.parametrize("grid", [None, (2, 4)])
    def test_factor_bitwise_identical_knob_on_off(self, grid):
        """DLAF_AUTOTUNE=0 vs =1 at the start rung: factor bytes
        identical (the ladders' start routes ARE the platform
        defaults)."""
        g = Grid(*grid) if grid else None
        mat = _hpd_matrix(48, 16, grid=g)
        os.environ["DLAF_AUTOTUNE"] = "0"
        os.environ["DLAF_LOG"] = "off"
        C.initialize()
        at._reset_for_tests()
        ref = cholesky("L", mat).to_numpy()
        os.environ["DLAF_AUTOTUNE"] = "1"
        C.initialize()
        at._reset_for_tests()
        got = cholesky("L", mat).to_numpy()
        assert np.array_equal(np.tril(ref), np.tril(got))

    def test_knob_off_emits_no_records_and_no_table(self, tmp_path):
        _arm(tmp_path, DLAF_AUTOTUNE="0")
        cholesky("L", _hpd_matrix())
        assert _records(tmp_path, "autotune") == []

    def test_probes_feed_the_table_per_op(self, tmp_path):
        _arm(tmp_path, DLAF_AUTOTUNE="1")
        mat = _hpd_matrix()
        fac = cholesky("L", mat)
        from dlaf_tpu.algorithms.gen_to_std import gen_to_std

        gen_to_std("L", mat, fac)
        rungs = {k: e["rung"] for k, e in at.get_table().snapshot().items()}
        assert "cholesky.n64.nb16.float64.cpu" in rungs
        assert "hegst.n64.nb16.float64.cpu" in rungs
        recs = _records(tmp_path, "autotune")
        assert {r["op"] for r in recs} >= {"cholesky", "hegst"}
        assert all(r["reason"] == "hold" for r in recs)
        assert validate_records(_records(tmp_path)) == []

    def test_donated_input_skips_the_probe(self, tmp_path):
        _arm(tmp_path, DLAF_AUTOTUNE="1")
        mat = _hpd_matrix()
        cholesky("L", mat, donate=True)
        assert _records(tmp_path, "autotune") == []

    def test_probe_cadence_knob(self, tmp_path):
        _arm(tmp_path, DLAF_AUTOTUNE="1", DLAF_AUTOTUNE_PROBE_EVERY="3")
        mat = _hpd_matrix()
        for _ in range(6):
            cholesky("L", mat)
        recs = _records(tmp_path, "autotune")
        assert len(recs) == 2        # calls 1 and 4 probe; the rest skip

    def test_breach_escalates_and_next_call_uses_the_new_route(
            self, tmp_path):
        """The closed loop end-to-end: a nan_tile-grade breach escalates
        the site (decision record + gauge transition), and the NEXT call
        dispatches under the escalated route."""
        from dlaf_tpu.health import inject

        _arm(tmp_path, DLAF_AUTOTUNE="1")
        mat = _hpd_matrix()
        poisoned = inject.nan_tile(mat, tile=(1, 0), element=(2, 3))
        cholesky("L", poisoned)                 # NaN factor -> breach
        recs = _records(tmp_path, "autotune")
        assert recs[-1]["reason"] == "escalate"
        assert recs[-1]["nonfinite"] is True and recs[-1]["probe"] is None
        assert recs[-1]["rung_new"] == F64.start + 1
        key = at.site_key("cholesky", n=48, nb=16, dtype=np.float64,
                          platform="cpu")
        assert at.get_table().rung_of(key) == F64.start + 1
        assert at.get_table().route_for(key, F64).as_dict() == \
            F64.rungs[F64.start + 1].as_dict()
        gauge = obs.registry().gauge("dlaf_autotune_route", op="cholesky",
                                     knob="rung").snapshot()
        assert gauge["value"] == F64.start + 1
        esc = obs.registry().counter("dlaf_autotune_escalations_total",
                                     op="cholesky").snapshot()
        assert esc["value"] == 1
        # clean calls afterwards hold, then relax after K comfortable
        for _ in range(int(C.get_configuration().autotune_relax_after)):
            cholesky("L", mat)
        recs = _records(tmp_path, "autotune")
        assert recs[-1]["reason"] == "relax"
        assert at.get_table().rung_of(key) == F64.start

    def test_exhaustion_strict_raise_and_flight_dump(self, tmp_path):
        from dlaf_tpu.health.errors import AutotuneExhaustedError

        _arm(tmp_path, DLAF_AUTOTUNE="1", DLAF_STRICT="1",
             DLAF_FLIGHT_RECORDER="32")
        key = at.site_key("cholesky", n=48, nb=16, dtype=np.float64,
                          platform="cpu")
        top = len(F64.rungs) - 1
        for _ in range(top - F64.start):
            at.observe_ratio(key, F64, 5.0)
        with pytest.raises(AutotuneExhaustedError) as err:
            at.observe_ratio(key, F64, 5.0)
        assert err.value.site == key.label and err.value.rung == top
        flight = str(tmp_path / "art.jsonl") + ".flight.jsonl"
        assert os.path.exists(flight)
        header = json.loads(open(flight).readline())
        assert header["reason"] == "autotune_exhausted"
        # the exhausted decision record itself rode the ring
        ring = [json.loads(line) for line in open(flight)][1:]
        assert any(r.get("type") == "autotune"
                   and r.get("reason") == "exhausted" for r in ring)

    def test_eigensolver_pipeline_steers_and_probes(self, tmp_path):
        _arm(tmp_path, DLAF_AUTOTUNE="1")
        from dlaf_tpu.eigensolver import eigensolver

        mat = _hpd_matrix(32, 8)
        res = eigensolver("L", mat)
        assert np.isfinite(res.eigenvalues).all()
        recs = _records(tmp_path, "autotune")
        ops = {r["op"] for r in recs}
        assert "eigensolver" in ops
        assert validate_records(_records(tmp_path)) == []


# ---------------------------------------------------------------------------
# Record schema + --require-autotune
# ---------------------------------------------------------------------------

def _decision_record(**over):
    rec = {"v": 1, "type": "autotune", "ts": 1.0,
           "site": "cholesky.n64.nb16.float64.cpu", "op": "cholesky",
           "n_bucket": 64, "nb": 16, "dtype": "float64", "platform": "cpu",
           "reason": "escalate", "rung_old": 3, "rung_new": 4,
           "route_old": {"f64_gemm_slices": 7},
           "route_new": {"f64_gemm_slices": 8},
           "probe": 2.0, "attrs": {}}
    rec.update(over)
    return rec


class TestSchemaAndValidator:
    def test_valid_record_passes(self):
        assert validate_records([_decision_record()]) == []

    @pytest.mark.parametrize("over,msg", [
        ({"reason": "panic"}, "reason"),
        ({"rung_new": 3}, "escalate must raise"),
        ({"reason": "relax", "rung_new": 5}, "relax must lower"),
        ({"reason": "hold", "rung_new": 9}, "hold must keep"),
        ({"probe": float("nan")}, "probe"),
        ({"probe": None}, "probe"),
        ({"probe": None, "nonfinite": True, "rung_new": 3}, "escalate"),
        ({"site": ""}, "site"),
        ({"route_new": "s8"}, "route_new"),
        ({"n_bucket": -1}, "n_bucket"),
    ])
    def test_schema_rejections(self, over, msg):
        errs = validate_records([_decision_record(**over)])
        assert errs and any(msg in e for e in errs), errs

    def test_require_autotune_needs_a_route_move(self):
        hold = _decision_record(reason="hold", rung_new=3,
                                route_new={"f64_gemm_slices": 7})
        errs = validate_records([hold], require_autotune=True)
        assert any("never moved a route" in e for e in errs)
        assert validate_records([_decision_record()],
                                require_autotune=True) == []

    def test_require_autotune_rejects_an_exhausted_end_state(self):
        moved = _decision_record()
        exhausted = _decision_record(reason="exhausted", rung_old=5,
                                     rung_new=5, probe=None,
                                     nonfinite=True,
                                     route_old={"f64_gemm_slices": 8,
                                                "f64_trsm": "native"},
                                     route_new={"f64_gemm_slices": 8,
                                                "f64_trsm": "native"})
        errs = validate_records([moved, exhausted], require_autotune=True)
        assert any("exhausted" in e for e in errs)
        # ...but an exhaustion RECOVERED by a later relax is no longer
        # an open state
        relaxed = _decision_record(reason="relax", rung_old=5, rung_new=4,
                                   probe=0.01,
                                   route_new={"f64_gemm_slices": 8})
        assert validate_records([moved, exhausted, relaxed],
                                require_autotune=True) == []

    def test_validate_cli_flag(self, tmp_path):
        path = tmp_path / "a.jsonl"
        path.write_text(json.dumps(_decision_record()) + "\n")
        proc = subprocess.run(
            [sys.executable, "-m", "dlaf_tpu.obs.validate", str(path),
             "--require-autotune"], capture_output=True, text=True,
            cwd=REPO)
        assert proc.returncode == 0, proc.stderr
        assert "1 autotune decisions" in proc.stdout
        hold = _decision_record(reason="hold", rung_new=3)
        path.write_text(json.dumps(hold) + "\n")
        proc = subprocess.run(
            [sys.executable, "-m", "dlaf_tpu.obs.validate", str(path),
             "--require-autotune"], capture_output=True, text=True,
            cwd=REPO)
        assert proc.returncode == 1

    def test_aggregate_decision_trail_section(self, tmp_path):
        from dlaf_tpu.obs.aggregate import (autotune_rows,
                                            format_autotune_trail)

        rows = autotune_rows([
            _decision_record(),
            _decision_record(reason="hold", rung_old=4, rung_new=4,
                             probe=0.5),
        ])
        assert rows[0]["count"] == 2 and rows[0]["escalations"] == 1
        lines = format_autotune_trail(rows)
        assert any("escalate" in line for line in lines)
        assert any("rung 3 -> 4" in line for line in lines)


# ---------------------------------------------------------------------------
# Serve: per-bucket routing + zero-steady-state retrace
# ---------------------------------------------------------------------------

class TestServeRouting:
    def _queue(self, bn=32, batch=4):
        from dlaf_tpu.serve import Queue

        return Queue(buckets=(bn,), batch=batch, deadline_s=1e9)

    def _problems(self, k, n=20, seed=0):
        rng = np.random.default_rng(seed)
        out = []
        for _ in range(k):
            x = rng.standard_normal((n, n))
            out.append(x @ x.T + n * np.eye(n))
        return out

    def test_bucket_spec_carries_the_table_route(self, tmp_path):
        _arm(tmp_path, DLAF_AUTOTUNE="1")
        q = self._queue()
        from dlaf_tpu.serve import Request

        spec = q._spec(q._key(Request(op="cholesky",
                                      a=self._problems(1)[0])))
        assert dict(spec.route) == F64.rungs[F64.start].as_dict()
        assert "rt_s7" in spec.site
        # knob off: the spec keeps its route-free identity
        os.environ["DLAF_AUTOTUNE"] = "0"
        C.initialize()
        spec0 = q._spec(q._key(Request(op="cholesky",
                                       a=self._problems(1)[0])))
        assert spec0.route == () and "rt_" not in spec0.site

    def test_steady_state_zero_retrace_and_route_change_is_new_program(
            self, tmp_path):
        """The tentpole zero-retrace pin (docs/autotune.md): a warmed
        bucket stream under a HELD route shows dlaf_retrace_total == 1
        per serve site; an escalation dispatches a NEW program (visible
        miss, its own site) and the old program still never retraces."""
        from dlaf_tpu.serve import Request
        from dlaf_tpu.serve.programs import _reset_for_tests

        _arm(tmp_path, DLAF_AUTOTUNE="1", DLAF_PROGRAM_TELEMETRY="1")
        _reset_for_tests()
        q = self._queue()
        probs = self._problems(8)
        reqs = [Request(op="cholesky", a=a) for a in probs]
        q.warmup(reqs)
        for a in probs:
            q.submit(Request(op="cholesky", a=a))
        q.flush()
        st = q.service.stats()
        assert st["misses"] == 0 and st["hit_rate"] == 1.0
        key = q._key(Request(op="cholesky", a=probs[0]))
        site_held = q._spec(key).site
        snap = obs.registry().counter("dlaf_retrace_total",
                                      site=site_held).snapshot()
        assert snap["value"] == 1, snap
        # force an escalation of the bucket's table entry
        tkey = at.site_key("cholesky", n=32, nb=32, dtype="float64",
                           platform="cpu")
        at.observe_ratio(tkey, F64, 5.0)
        site_esc = q._spec(key).site
        assert site_esc != site_held
        q.submit(Request(op="cholesky", a=probs[0]))
        q.flush()
        assert q.service.stats()["misses"] == 1    # the new route compiles
        for site in (site_held, site_esc):
            snap = obs.registry().counter("dlaf_retrace_total",
                                          site=site).snapshot()
            assert snap["value"] == 1, (site, snap)

    def test_strict_exhaustion_is_not_a_dispatch_failure(self, tmp_path):
        """A strict AutotuneExhaustedError out of a serve dispatch's
        probe surfaces to the caller but the dispatch itself SUCCEEDED:
        tickets fulfilled, counted as a dispatch (never a failure), so
        stats()['dispatches'] stays in agreement with the dispatch
        records (the /healthz agreement leg)."""
        from dlaf_tpu.health.errors import AutotuneExhaustedError
        from dlaf_tpu.serve import Request
        from dlaf_tpu.serve.programs import _reset_for_tests

        _arm(tmp_path, DLAF_AUTOTUNE="1", DLAF_ACCURACY="1",
             DLAF_STRICT="1")
        _reset_for_tests()
        q = self._queue()
        tkey = at.site_key("cholesky", n=32, nb=32, dtype="float64",
                           platform="cpu")
        top = len(F64.rungs) - 1
        for _ in range(top - F64.start):     # walk the entry to the top
            at.observe_ratio(tkey, F64, 5.0)
        bad = np.full((20, 20), np.nan)      # NaN residual at the top
        ticket = q.submit(Request(op="cholesky", a=bad))
        with pytest.raises(AutotuneExhaustedError):
            q.flush()
        assert ticket.done and ticket.error is None
        st = q.stats()
        assert st["dispatches"] == 1
        bucket = next(iter(st["buckets"].values()))
        assert bucket["dispatches"] == 1 and bucket["failures"] == 0
        disp = [r for r in _records(tmp_path, "serve")
                if r["event"] == "dispatch"]
        assert len(disp) == st["dispatches"]

    def test_serve_residuals_feed_the_bucket_entry(self, tmp_path):
        from dlaf_tpu.serve import Request
        from dlaf_tpu.serve.programs import _reset_for_tests

        _arm(tmp_path, DLAF_AUTOTUNE="1", DLAF_ACCURACY="1")
        _reset_for_tests()
        q = self._queue()
        probs = self._problems(4)
        q.warmup([Request(op="cholesky", a=probs[0])])
        for a in probs:
            q.submit(Request(op="cholesky", a=a))
        q.flush()
        recs = _records(tmp_path, "autotune")
        assert recs and all(r["attrs"].get("source") == "serve"
                            for r in recs)
        assert at.get_table().rung_of(
            at.site_key("cholesky", n=32, nb=32, dtype="float64",
                        platform="cpu")) == F64.start


# ---------------------------------------------------------------------------
# Satellite 1: the ozaki_impl=pallas ladder rung
# ---------------------------------------------------------------------------

class TestOzakiPallasRung:
    def _force_rung0(self, tmp_path):
        _arm(tmp_path, DLAF_AUTOTUNE="1", DLAF_F64_GEMM="mxu",
             DLAF_F64_GEMM_MIN_DIM="8")
        key = at.site_key("cholesky", n=64, nb=8, dtype=np.float64,
                          platform="cpu")
        # walk the table to the fastest rung deterministically
        table = at.get_table()
        for _ in range(F64.start * int(
                C.get_configuration().autotune_relax_after)):
            table.observe(key, F64, 0.0, margin=0.25, relax_after=int(
                C.get_configuration().autotune_relax_after), budget=0)
        assert table.rung_of(key) == 0
        assert table.route_for(key, F64).ozaki_impl == "pallas"
        return key

    def test_rung0_selects_the_fused_pallas_reduction(self, tmp_path,
                                                      devices8):
        """The revived fused Ozaki slice kernels are selectable by the
        route ladder: at rung 0 the distributed cholesky runs the
        predicated masked kernel (interpret mode here) and matches the
        jnp route."""
        self._force_rung0(tmp_path)
        mat = _hpd_matrix(64, 8, grid=Grid(2, 4))
        a = mat.to_numpy()
        got = cholesky("L", mat).to_numpy()
        f = np.tril(got)
        resid = np.linalg.norm(f @ f.T - a) / np.linalg.norm(a)
        assert resid < 60 * 64 * np.finfo(np.float64).eps
        # the jnp-route reference under the SAME slice count (rung 0 is
        # s=5 + pallas; pin s=5 + jnp explicitly)
        os.environ["DLAF_AUTOTUNE"] = "0"
        os.environ["DLAF_F64_GEMM_SLICES"] = "5"
        C.initialize()
        try:
            ref = cholesky("L", mat).to_numpy()
        finally:
            os.environ.pop("DLAF_F64_GEMM_SLICES", None)
        assert np.abs(np.tril(got) - np.tril(ref)).max() < 1e-10

    def test_rung0_drillable_via_disable_ozaki(self, tmp_path, devices8):
        """inject.disable_ozaki degrades the whole mxu route under the
        fastest rung — counted at ozaki_gemm, correct result, and
        DLAF_STRICT raises (the route must be drill-able even while the
        tunnel blocks real pallas compiles)."""
        from dlaf_tpu.health import inject
        from dlaf_tpu.health.errors import DegradationError

        self._force_rung0(tmp_path)
        mat = _hpd_matrix(64, 8, grid=Grid(2, 4))
        a = mat.to_numpy()
        with inject.disable_ozaki():
            got = cholesky("L", mat).to_numpy()
            snap = obs.registry().counter(
                "dlaf_fallback_total", site="ozaki_gemm",
                reason="injected_off").snapshot()
            assert snap["value"] >= 1
        f = np.tril(got)
        assert np.linalg.norm(f @ f.T - a) / np.linalg.norm(a) \
            < 60 * 64 * np.finfo(np.float64).eps
        os.environ["DLAF_STRICT"] = "1"
        C.initialize()
        with inject.disable_ozaki():
            with pytest.raises(DegradationError):
                cholesky("L", mat)


# ---------------------------------------------------------------------------
# Bench-gate autotune leg
# ---------------------------------------------------------------------------

class TestBenchGateLeg:
    def _line(self, speedup, n=192):
        return {"variant": "autotune", "platform": "cpu", "dtype":
                "float64", "n": n, "nb": 64, "gflops": 1.0, "t": 0.1,
                "ts": "2026-08-04T00:00:00", "source": "bench.py",
                "workload": "autotune", "speedup": speedup}

    def test_speedup_floor_trips_and_passes(self):
        from bench_gate import run_gate

        logs = []
        bad = run_gate([], [self._line(0.2)], tolerance=0.1,
                       min_history=3, best_k=3, log=logs.append,
                       min_autotune_speedup=0.5)
        assert bad == 1 and any("ISSUE-15" in line for line in logs)
        ok = run_gate([], [self._line(0.9)], tolerance=0.1,
                      min_history=3, best_k=3, log=lambda *a: None,
                      min_autotune_speedup=0.5)
        assert ok == 0

    def test_committed_history_line_gates_on_replay(self):
        """A committed autotune history line keeps the floor enforced in
        every --replay (the serve-line convention)."""
        from dlaf_tpu.obs.sinks import read_history_records

        history = read_history_records(
            os.path.join(REPO, ".bench_history.jsonl"))
        lines = [line for line in history
                 if line.get("workload") == "autotune"]
        assert lines, "no committed autotune history line"
        assert all(isinstance(line.get("speedup"), float)
                   for line in lines)
