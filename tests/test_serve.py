"""Tests for ISSUE 11: the batched many-problem serving layer.

Covers: batched-vs-loop-of-singles BITWISE parity for the three batched
entry points (dtype x uplo x occupancy), pad-lane inertness and the
shape-padding budget, program-service cache semantics (hit/miss/warmup/
pin/evict, LRU byte budget, config invalidation), zero-retrace-after-
warmup pinned on ``dlaf_retrace_total``, queue bucket-selection and
deadline determinism (fake clock), the ``serve`` record schema +
``--require-serve`` validator obligation, per-lane
``robust_cholesky_batched`` recovery, the bench serve arm's headline
isolation, the bench-gate serve-speedup leg, and the graphcheck serve
program specs (docs/serving.md).
"""

import functools
import json
import os
import sys

import numpy as np
import pytest

import jax

import dlaf_tpu.config as C
from dlaf_tpu import health, obs
from dlaf_tpu.algorithms import batched as bt
from dlaf_tpu.serve import (ProgramService, Queue, Request, bucket_ceiling,
                            cholesky_batched, cholesky_spec, eigh_batched,
                            eigh_spec, get_service, solve_batched,
                            solve_spec)
from dlaf_tpu.serve import programs as serve_programs

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)


@pytest.fixture(autouse=True)
def serve_reset():
    """Each test leaves the default (unobserved) config and an empty
    default service behind."""
    yield
    for key in ("DLAF_METRICS_PATH", "DLAF_PROGRAM_TELEMETRY",
                "DLAF_ACCURACY", "DLAF_SERVE_BUCKETS", "DLAF_SERVE_BATCH",
                "DLAF_SERVE_DEADLINE_MS", "DLAF_SERVE_CACHE_BYTES"):
        os.environ.pop(key, None)
    obs._reset_for_tests()
    obs.telemetry._reset_for_tests()
    serve_programs._reset_for_tests()
    health.circuit.reset()            # a tripped dispatch breaker must
    C.finalize()                      # not fail-fast later tests' buckets
    C.initialize()


def _hpd(n, seed=0, dtype=np.float64, shift=None):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n)).astype(dtype)
    return (x @ x.T + (n if shift is None else shift)
            * np.eye(n)).astype(dtype)


def _hpd_batch(b, n, dtype=np.float64, seed=0):
    return np.stack([_hpd(n, seed=seed + i, dtype=dtype) for i in range(b)])


def _tri(n, uplo="L", seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n)).astype(dtype)
    t = np.tril(x) if uplo == "L" else np.triu(x)
    return (t + 3 * np.eye(n)).astype(dtype)


def _sym(n, seed=0, dtype=np.float64):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n)).astype(dtype)
    return ((x + x.T) / 2).astype(dtype)


# ---------------------------------------------------------------------------
# Batched-vs-loop-of-singles bitwise parity (the core contract)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [np.float64, np.float32])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_cholesky_batched_bitwise_vs_singles(dtype, uplo):
    """Every lane of a batched dispatch == the B=1 dispatch of the same
    bucket program == the unbatched singleton kernel, bitwise; info
    vector all zero on SPD lanes."""
    svc = ProgramService()
    n, b = 20, 4
    a = _hpd_batch(b, n, dtype=dtype)
    out, info = cholesky_batched(uplo, a, with_info=True, service=svc)
    out = np.asarray(out)
    assert out.shape == (b, n, n) and np.asarray(info).tolist() == [0] * b
    single = jax.jit(functools.partial(
        bt.cholesky_one, uplo=uplo, nb=bt.default_nb(n), with_info=True))
    for i in range(b):
        lane1, info1 = cholesky_batched(uplo, a[i:i + 1], with_info=True,
                                        service=svc)
        np.testing.assert_array_equal(out[i], np.asarray(lane1)[0])
        s_out, s_info = single(a[i])
        np.testing.assert_array_equal(out[i], np.asarray(s_out))
        assert int(np.asarray(info1)[0]) == int(s_info) == 0


@pytest.mark.parametrize("side,uplo,op,diag", [
    ("L", "L", "N", "N"), ("L", "U", "T", "N"),
    ("R", "U", "N", "U"), ("R", "L", "C", "N"),
])
def test_solve_batched_bitwise_vs_singles(side, uplo, op, diag):
    """Batched solve lanes == B=1 dispatches bitwise for every
    side/uplo/op/diag family, and solve the system they claim to."""
    svc = ProgramService()
    n, nrhs, b = 16, 5, 3
    a = np.stack([_tri(n, uplo=uplo, seed=i) for i in range(b)])
    rng = np.random.default_rng(7)
    shape = (b, n, nrhs) if side == "L" else (b, nrhs, n)
    rhs = rng.standard_normal(shape)
    x, info = solve_batched(side, uplo, op, diag, 1.0, a, rhs,
                            with_info=True, service=svc)
    x = np.asarray(x)
    assert np.asarray(info).tolist() == [0] * b
    for i in range(b):
        x1, _ = solve_batched(side, uplo, op, diag, 1.0, a[i:i + 1],
                              rhs[i:i + 1], with_info=True, service=svc)
        np.testing.assert_array_equal(x[i], np.asarray(x1)[0])
        # the solve actually solves: op(T) X = B / X op(T) = B
        t = np.tril(a[i]) if uplo == "L" else np.triu(a[i])
        if diag == "U":
            np.fill_diagonal(t, 1.0)
        t = {"N": t, "T": t.T, "C": t.conj().T}[op]
        lhs = t @ x[i] if side == "L" else x[i] @ t
        np.testing.assert_allclose(lhs, rhs[i], atol=1e-10)


def test_solve_batched_per_lane_alpha():
    """alpha is a traced per-lane vector, never a bucket key: two
    dispatches with different alphas share one program, and each lane
    honors its own scale."""
    svc = ProgramService()
    n, b = 12, 3
    a = np.stack([_tri(n, seed=i) for i in range(b)])
    rhs = np.random.default_rng(1).standard_normal((b, n, 4))
    alphas = np.array([1.0, -2.0, 0.5])
    x = np.asarray(solve_batched("L", "L", "N", "N", alphas, a, rhs,
                                 with_info=False, service=svc))
    for i in range(b):
        np.testing.assert_allclose(np.tril(a[i]) @ x[i],
                                   alphas[i] * rhs[i], atol=1e-10)
    assert svc.stats()["entries"] == 1


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_eigh_batched_bitwise_vs_singles(uplo):
    """Batched eigh lanes == B=1 dispatches == the unbatched singleton
    kernel, bitwise; only the ``uplo`` triangle is read."""
    svc = ProgramService()
    n, b = 16, 3
    a = np.stack([_sym(n, seed=i) for i in range(b)])
    # poison the ignored triangle: the entry must not read it
    poison = np.full((n, n), 1e30)
    a_stored = np.where(np.tril(np.ones((n, n)), 0 if uplo == "L" else n)
                        .astype(bool) if uplo == "L"
                        else np.triu(np.ones((n, n))).astype(bool),
                        a, poison)
    w, v, info = eigh_batched(uplo, a_stored, with_info=True, service=svc)
    w, v = np.asarray(w), np.asarray(v)
    assert np.asarray(info).tolist() == [0] * b
    single = jax.jit(functools.partial(bt.eigh_one, uplo=uplo,
                                       with_info=True))
    for i in range(b):
        w1, v1, _ = eigh_batched(uplo, a_stored[i:i + 1], with_info=True,
                                 service=svc)
        np.testing.assert_array_equal(w[i], np.asarray(w1)[0])
        np.testing.assert_array_equal(v[i], np.asarray(v1)[0])
        sw, sv, _ = single(a_stored[i])
        np.testing.assert_array_equal(w[i], np.asarray(sw))
        np.testing.assert_array_equal(v[i], np.asarray(sv))
        # the decomposition is of the triangle's hermitian expansion
        np.testing.assert_allclose(a[i] @ v[i], v[i] * w[i][None, :],
                                   atol=1e-12 * n)


def test_pad_lanes_inert_and_identity():
    """Occupancy invariance: real-lane results are bitwise unchanged
    whether the other lanes hold problems or identity padding, and the
    pad lanes factor to exactly the singleton identity result."""
    svc = ProgramService()
    n, b = 16, 4
    full = _hpd_batch(b, n)
    padded = full.copy()
    padded[2:] = np.eye(n)
    out_full, _ = cholesky_batched("L", full, with_info=True, service=svc)
    out_pad, info_pad = cholesky_batched("L", padded, with_info=True,
                                         service=svc)
    out_full, out_pad = np.asarray(out_full), np.asarray(out_pad)
    np.testing.assert_array_equal(out_full[:2], out_pad[:2])
    assert np.asarray(info_pad).tolist() == [0] * b
    eye1, _ = cholesky_batched("L", np.eye(n)[None], with_info=True,
                               service=svc)
    for i in (2, 3):
        np.testing.assert_array_equal(out_pad[i], np.asarray(eye1)[0])


def test_batched_info_flags_failed_lanes_only():
    """Per-element info: indefinite lanes report their failing column,
    clean lanes report 0, and the factor bytes of clean lanes match the
    all-clean batch (failure containment across lanes)."""
    svc = ProgramService()
    n = 12
    good = _hpd_batch(3, n)
    mixed = good.copy()
    mixed[1] = _hpd(n, seed=9, shift=-100.0)     # indefinite lane
    out_good, info_good = cholesky_batched("L", good, with_info=True,
                                           service=svc)
    out_mixed, info_mixed = cholesky_batched("L", mixed, with_info=True,
                                             service=svc)
    assert np.asarray(info_good).tolist() == [0, 0, 0]
    infos = np.asarray(info_mixed)
    assert infos[0] == 0 and infos[2] == 0 and infos[1] >= 1
    np.testing.assert_array_equal(np.asarray(out_good)[0],
                                  np.asarray(out_mixed)[0])
    np.testing.assert_array_equal(np.asarray(out_good)[2],
                                  np.asarray(out_mixed)[2])


def test_shape_padding_budgeted_not_bitwise():
    """The queue's identity-border shape padding: the padded region is
    exactly inert and the real block matches the exact-size program at
    ulp level (the documented budget, docs/serving.md)."""
    svc = ProgramService()
    n_req, bn = 13, 16
    a = _hpd(n_req, seed=3)
    ap = np.eye(bn)
    ap[:n_req, :n_req] = a
    out_p, info_p = cholesky_batched("L", ap[None], with_info=True,
                                     service=svc)
    out_s, _ = cholesky_batched("L", a[None], with_info=True, service=svc)
    out_p, out_s = np.asarray(out_p)[0], np.asarray(out_s)[0]
    assert int(np.asarray(info_p)[0]) == 0
    # pad region exactly inert
    np.testing.assert_array_equal(np.tril(out_p)[n_req:, n_req:],
                                  np.eye(bn - n_req))
    assert np.abs(np.tril(out_p)[n_req:, :n_req]).max() == 0.0
    # real block within a few ulp of the exact-size factor
    np.testing.assert_allclose(out_p[:n_req, :n_req], out_s,
                               rtol=0, atol=64 * np.finfo(np.float64).eps
                               * np.abs(out_s).max())


# ---------------------------------------------------------------------------
# Program service: cache semantics
# ---------------------------------------------------------------------------

def _spec(n=12, b=2, **kw):
    kw.setdefault("dtype", "float64")
    kw.setdefault("uplo", "L")
    return cholesky_spec(batch=b, n=n, nb=n, **kw)


def test_cache_hit_miss_and_stats():
    svc = ProgramService()
    spec = _spec()
    a = _hpd_batch(2, 12)
    svc.run(spec, a)                      # miss + compile
    svc.run(spec, a)                      # hit
    st = svc.stats()
    assert st["misses"] == 1 and st["hits"] == 1 and st["compiles"] == 1
    assert st["entries"] == 1 and st["bytes"] > 0
    assert st["hit_rate"] == 0.5


def test_warmup_counts_warmup_not_miss_and_is_idempotent():
    svc = ProgramService()
    spec = _spec()
    walls = svc.warmup(spec)
    assert walls[spec] > 0
    assert svc.warmup(spec)[spec] == 0.0      # already warm
    st = svc.stats()
    assert st["warmups"] == 1 and st["misses"] == 0 and st["compiles"] == 1
    svc.run(spec, _hpd_batch(2, 12))
    st = svc.stats()
    assert st["hits"] == 1 and st["misses"] == 0 and st["hit_rate"] == 1.0


def test_zero_retrace_and_full_hit_rate_after_warmup(tmp_path):
    """The ISSUE-11 steady-state acceptance pin: after warmup, an
    in-bucket stream shows dlaf_retrace_total == 1 per serve site (the
    warmup trace — never a retrace) and cache hit rate == 1.0."""
    C.initialize(C.Configuration(
        metrics_path=str(tmp_path / "m.jsonl"), program_telemetry=True))
    svc = ProgramService()
    spec = _spec(n=14, b=3)
    svc.warmup(spec)
    a = _hpd_batch(3, 14)
    for _ in range(5):
        svc.run(spec, a)
    st = svc.stats()
    assert st["hit_rate"] == 1.0 and st["misses"] == 0
    snap = obs.registry().counter("dlaf_retrace_total",
                                  site=spec.site).snapshot()
    assert snap["value"] == 1, snap
    # an evict forces the recompile the counter exists to expose
    assert svc.evict(spec)
    svc.run(spec, a)
    snap = obs.registry().counter("dlaf_retrace_total",
                                  site=spec.site).snapshot()
    assert snap["value"] == 2, snap
    assert svc.stats()["misses"] == 1


def test_lru_byte_budget_evicts_oldest_unpinned():
    svc = ProgramService(cache_bytes=1)       # everything over budget
    s1, s2 = _spec(n=8), _spec(n=12)
    svc.warmup(s1)
    assert svc.specs() == ()                  # evicted immediately
    st = svc.stats()
    assert st["evictions"] == 1
    # pinned programs are never budget-evicted
    svc.pin(s2)
    assert svc.specs() == (s2,)
    svc.warmup(s1)
    assert s2 in svc.specs()                  # survived; s1 evicted
    assert s1 not in svc.specs()


def test_lru_recency_order():
    """Hits refresh recency: with a budget fitting two programs, the
    least-recently-USED one is evicted, not the oldest-inserted."""
    svc = ProgramService()                    # unbounded while warming
    s1, s2, s3 = _spec(n=8), _spec(n=8, uplo="U"), _spec(n=8, b=2,
                                                         with_info=False)
    svc.warmup(s1, s2)
    e1 = svc._entries[s1].nbytes
    e2 = svc._entries[s2].nbytes
    svc.run(s1, _hpd_batch(2, 8))             # s1 most-recent
    svc._cache_bytes = e1 + e2                # room for exactly two
    svc.warmup(s3)                            # forces one eviction
    assert s2 not in svc.specs()              # LRU victim, not s1
    assert s1 in svc.specs() and s3 in svc.specs()


def test_explicit_evict_and_unpin():
    svc = ProgramService()
    spec = _spec()
    assert svc.evict(spec) is False           # not resident
    svc.pin(spec)
    assert svc.stats()["pins"] == 1
    assert svc.evict(spec) is True            # explicit evict beats pin
    svc.pin(spec)
    svc.unpin(spec)
    svc._cache_bytes = 1
    svc._evict_for_budget()
    assert spec not in svc.specs()            # unpinned -> evictable


def test_config_change_clears_default_service():
    svc = get_service()
    spec = _spec()
    svc.warmup(spec)
    assert spec in svc.specs()
    C.initialize(C.Configuration(serve_batch=5))   # differing config
    assert svc.specs() == ()


def test_spec_site_labels_are_distinct_and_bounded():
    specs = [_spec(n=8), _spec(n=8, b=4), _spec(n=16),
             solve_spec(batch=2, n=8, nrhs=3, nb=8, dtype="float64"),
             eigh_spec(batch=2, n=8, nb=8, dtype="float64"),
             _spec(n=8, donate=True)]
    sites = [s.site for s in specs]
    assert len(set(sites)) == len(sites)
    assert all(s.startswith("serve.") for s in sites)


# ---------------------------------------------------------------------------
# Queue: bucket policy, deadlines, determinism
# ---------------------------------------------------------------------------

def test_bucket_ceiling_policy():
    assert bucket_ceiling(17, (32, 64)) == 32
    assert bucket_ceiling(32, (32, 64)) == 32
    assert bucket_ceiling(33, (32, 64)) == 64
    # above the largest ceiling / no explicit list: next power of two
    assert bucket_ceiling(65, (32, 64)) == 128
    assert bucket_ceiling(5, ()) == 8
    assert bucket_ceiling(100, ()) == 128
    with pytest.raises(Exception):
        bucket_ceiling(0, ())


def test_serve_knob_validation():
    with pytest.raises(ValueError):
        C.initialize(C.Configuration(serve_batch=0))
    with pytest.raises(ValueError):
        C.initialize(C.Configuration(serve_deadline_ms=-1.0))
    with pytest.raises(ValueError):
        C.initialize(C.Configuration(serve_cache_bytes=-5))
    with pytest.raises(ValueError):
        C.initialize(C.Configuration(serve_buckets="64,32"))
    with pytest.raises(ValueError):
        C.initialize(C.Configuration(serve_buckets="a,b"))
    cfg = C.initialize(C.Configuration(serve_buckets="32,64"))
    assert C.parse_serve_buckets(cfg.serve_buckets) == (32, 64)


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_queue_full_batch_dispatches_immediately():
    svc = ProgramService()
    clock = _FakeClock()
    q = Queue(svc, batch=3, deadline_s=1e9, buckets=(16,), clock=clock)
    t1 = q.submit(Request(op="cholesky", a=_hpd(12, seed=1)))
    t2 = q.submit(Request(op="cholesky", a=_hpd(14, seed=2)))
    assert not t1.done and q.pending() == 2
    t3 = q.submit(Request(op="cholesky", a=_hpd(16, seed=3)))
    assert t1.done and t2.done and t3.done and q.pending() == 0
    assert q.dispatches == 1
    for t in (t1, t2, t3):
        a = np.asarray(t.request.a)
        fac = np.tril(t.result())
        assert fac.shape == a.shape
        np.testing.assert_allclose(fac @ fac.T,
                                   np.tril(a) + np.tril(a, -1).T,
                                   atol=1e-10 * len(a))
        assert t.info == 0 and t.total_s >= 0.0


def test_queue_deadline_determinism_with_fake_clock():
    svc = ProgramService()
    clock = _FakeClock()
    q = Queue(svc, batch=4, deadline_s=0.05, buckets=(16,), clock=clock)
    t1 = q.submit(Request(op="cholesky", a=_hpd(10)))
    clock.t = 0.049
    assert q.poll() == 0 and not t1.done       # under deadline: holds
    clock.t = 0.051
    assert q.poll() == 1 and t1.done           # expired: dispatches
    assert q.dispatches == 1
    # a submit is also a clock edge for OTHER buckets' deadlines
    t2 = q.submit(Request(op="cholesky", a=_hpd(10, seed=4)))
    clock.t = 0.2
    t3 = q.submit(Request(op="eigh", a=_sym(12)))
    assert t2.done                             # cholesky bucket expired
    assert not t3.done                         # eigh bucket is fresh
    q.flush()
    assert t3.done


def test_queue_bucket_keys_separate_ops_dtypes_and_flags():
    svc = ProgramService()
    q = Queue(svc, batch=8, deadline_s=1e9, buckets=(16,),
              clock=_FakeClock())
    q.submit(Request(op="cholesky", a=_hpd(12)))
    q.submit(Request(op="cholesky", a=_hpd(12).astype(np.float32)))
    q.submit(Request(op="cholesky", a=_hpd(12), uplo="U"))
    q.submit(Request(op="eigh", a=_sym(12)))
    q.submit(Request(op="solve", a=_tri(12),
                     b=np.ones((12, 3))))
    assert len(q._pending) == 5               # five distinct bucket keys
    assert q.flush() == 5


def test_queue_solve_roundtrip_with_rhs_bucketing():
    svc = ProgramService()
    q = Queue(svc, batch=2, deadline_s=1e9, buckets=(16,),
              clock=_FakeClock())
    a1, b1 = _tri(12, seed=1), np.random.default_rng(0).standard_normal(
        (12, 5))
    a2, b2 = _tri(10, seed=2), np.random.default_rng(1).standard_normal(
        (10, 7))
    t1 = q.submit(Request(op="solve", a=a1, b=b1, alpha=2.0))
    t2 = q.submit(Request(op="solve", a=a2, b=b2))
    assert t1.done and t2.done                # same (n=16, rhs=8) bucket
    x1, x2 = t1.result(), t2.result()
    assert x1.shape == b1.shape and x2.shape == b2.shape
    np.testing.assert_allclose(np.tril(a1) @ x1, 2.0 * b1, atol=1e-10)
    np.testing.assert_allclose(np.tril(a2) @ x2, b2, atol=1e-10)


def test_rhs_ceiling_is_pow2_not_matrix_bucket():
    """The rhs free-axis width never rounds to the MATRIX bucket list: a
    1-column rhs in a 512-bucket config would otherwise pay 512x the
    rhs work per solve (review finding on the first cut)."""
    from dlaf_tpu.serve import rhs_ceiling

    assert rhs_ceiling(1) == 1
    assert rhs_ceiling(3) == 4
    assert rhs_ceiling(8) == 8
    assert rhs_ceiling(9) == 16
    svc = ProgramService()
    q = Queue(svc, batch=1, deadline_s=1e9, buckets=(512,),
              clock=_FakeClock())
    t = q.submit(Request(op="solve", a=_tri(12),
                         b=np.ones((12, 1))))
    (spec,) = svc.specs()
    assert spec.n == 512 and spec.nrhs == 1   # not 512
    np.testing.assert_allclose(np.tril(_tri(12)) @ t.result(),
                               np.ones((12, 1)), atol=1e-10)


def test_queue_eigh_shape_pad_recovers_leading_pairs():
    """The eigh shape-padding contract: the pad block's eigenvalues sort
    strictly last, so the leading n_req pairs are the request's — pad
    rows of the returned vectors are exactly zero."""
    svc = ProgramService()
    q = Queue(svc, batch=1, deadline_s=1e9, buckets=(16,),
              clock=_FakeClock())
    a = _sym(11, seed=5)
    t = q.submit(Request(op="eigh", a=a))
    w, v = t.result()
    assert w.shape == (11,) and v.shape == (11, 11)
    ws, vs = np.linalg.eigh(a)
    np.testing.assert_allclose(w, ws, atol=1e-12)
    np.testing.assert_allclose(np.abs(v), np.abs(vs), atol=1e-10)
    np.testing.assert_allclose(a @ v, v * w[None, :], atol=1e-11)


def test_queue_eigh_shape_pad_dominant_eigenvalue():
    """Review-finding regression: the pad constant must dominate the
    SPECTRAL RADIUS, not max|A| — the all-ones matrix (rho = n, max|A|
    = 1) must come back with its dominant eigenpair intact."""
    svc = ProgramService()
    q = Queue(svc, batch=1, deadline_s=1e9, buckets=(16,),
              clock=_FakeClock())
    n = 8
    a = np.ones((n, n))
    t = q.submit(Request(op="eigh", a=a))
    w, v = t.result()
    ws, _ = np.linalg.eigh(a)
    np.testing.assert_allclose(w, ws, atol=1e-12)      # incl. lambda = n
    assert abs(w[-1] - n) < 1e-12
    np.testing.assert_allclose(a @ v, v * w[None, :], atol=1e-11)


def test_ticket_result_before_dispatch_raises():
    svc = ProgramService()
    q = Queue(svc, batch=4, deadline_s=1e9, buckets=(16,),
              clock=_FakeClock())
    t = q.submit(Request(op="cholesky", a=_hpd(8)))
    with pytest.raises(RuntimeError, match="still queued"):
        t.result()


def test_queue_rejects_malformed_requests():
    q = Queue(ProgramService(), batch=2, clock=_FakeClock())
    with pytest.raises(Exception):
        q.submit(Request(op="lu", a=_hpd(8)))
    with pytest.raises(Exception):
        q.submit(Request(op="cholesky", a=np.ones((3, 4))))
    with pytest.raises(Exception):
        q.submit(Request(op="solve", a=_tri(8), b=np.ones((5, 2))))
    with pytest.raises(Exception, match="dtype"):
        # mixed dtypes would poison the whole co-batched dispatch deep
        # inside the compiled executable: reject at submit
        q.submit(Request(op="solve", a=_tri(8).astype(np.float32),
                         b=np.ones((8, 2), np.float64)))


def test_dispatch_failure_poisons_tickets_with_cause():
    """A dispatch-time exception must not strand co-batched requests as
    forever-'queued': every ticket carries the cause, result() re-raises
    it, and the queue is not wedged for later requests."""

    class _BoomService(ProgramService):
        def run(self, spec, *args):
            raise RuntimeError("XLA exploded")

    q = Queue(_BoomService(), batch=2, deadline_s=1e9, buckets=(16,),
              clock=_FakeClock())
    t1 = q.submit(Request(op="cholesky", a=_hpd(8, seed=0)))
    with pytest.raises(RuntimeError, match="XLA exploded"):
        q.submit(Request(op="cholesky", a=_hpd(8, seed=1)))
    assert t1.error is not None and not t1.done
    with pytest.raises(RuntimeError, match="dispatch failed") as exc:
        t1.result()
    assert "XLA exploded" in str(exc.value.__cause__)
    assert q.pending() == 0                   # bucket not wedged


def test_queue_threaded_submits_race_free():
    """Concurrent submits into one bucket must never double-pop it: all
    requests dispatch exactly once and every ticket completes."""
    import threading as _threading

    svc = ProgramService()
    q = Queue(svc, batch=4, deadline_s=1e9, buckets=(16,))
    svc.warmup(*q.warmup_specs([Request(op="cholesky", a=_hpd(12))]))
    tickets, errors = [], []

    def worker(seed):
        try:
            tickets.append(q.submit(Request(op="cholesky",
                                            a=_hpd(12, seed=seed))))
        except Exception as e:               # noqa: BLE001 — recorded
            errors.append(e)

    threads = [_threading.Thread(target=worker, args=(i,))
               for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    q.flush()
    assert errors == []
    assert len(tickets) == 16 and all(t.done for t in tickets)
    assert q.dispatches == 4 and q.pending() == 0


# ---------------------------------------------------------------------------
# Queue.drain() — the explicit graceful-shutdown API (ISSUE 18 satellite)
# ---------------------------------------------------------------------------

def test_queue_drain_returns_undispatched_and_poisons_tickets():
    """drain() hands back every UNDISPATCHED (request, ticket) pair in
    submission order, empties the queue, and poisons each ticket with a
    structured DrainedError — result() names the cause instead of
    claiming "still queued"."""
    from dlaf_tpu.health.errors import DrainedError

    svc = ProgramService()
    q = Queue(svc, batch=4, deadline_s=1e9, buckets=(16,),
              clock=_FakeClock())
    done = q.submit(Request(op="cholesky", a=_hpd(12, seed=9)))
    q.flush()                          # dispatched: NOT drainable
    assert done.done
    reqs = [Request(op="cholesky", a=_hpd(12, seed=i)) for i in range(3)]
    reqs.append(Request(op="eigh", a=_sym(12)))
    tickets = [q.submit(r) for r in reqs]
    assert q.pending() == 4

    drained = q.drain()
    assert q.pending() == 0
    assert [r.rid for r, _ in drained] == [r.rid for r in reqs]
    assert [t for _, t in drained] == tickets
    assert done not in [t for _, t in drained]
    for req, t in drained:
        assert not t.done and isinstance(t.error, DrainedError)
        assert t.error.rid == req.rid and t.error.site == "serve.queue"
        assert t.error.bucket_n == 16
        with pytest.raises(RuntimeError,
                           match="drained undispatched") as ei:
            t.result()
        assert ei.value.__cause__ is t.error
    assert {t.error.op for _, t in drained} == {"cholesky", "eigh"}
    # drained tickets never resurface on later clock edges
    assert q.poll(now=1e12) == 0 and q.flush() == 0
    assert q.drain() == []             # idempotent on an empty queue
    # and the queue still serves fresh work afterwards
    t2 = q.submit(Request(op="cholesky", a=_hpd(12, seed=77)))
    q.flush()
    assert t2.done and np.tril(t2.result()).shape == (12, 12)


def test_queue_drain_stats_records_metrics_agree(tmp_path):
    """One drain, three observers — stats()['drained'], the resilience
    ``drain`` records, and ``dlaf_serve_drained_total{op}`` — must all
    report the SAME counts, joinable per request by trace ID."""
    path = str(tmp_path / "drain.jsonl")
    C.initialize(C.Configuration(metrics_path=path, log="off"))
    svc = ProgramService()
    q = Queue(svc, batch=8, deadline_s=1e9, buckets=(16,),
              clock=_FakeClock())
    tickets = [q.submit(Request(op="cholesky", a=_hpd(12, seed=i)))
               for i in range(3)]
    tickets += [q.submit(Request(op="eigh", a=_sym(12, seed=i)))
                for i in range(2)]

    drained = q.drain()
    assert len(drained) == 5
    st = q.stats()
    assert st["pending"] == 0 and st["drained"] == 5
    by_site = {site: b["drained"] for site, b in st["buckets"].items()
               if b["drained"]}
    assert sorted(by_site.values()) == [2, 3]
    assert all(b["depth"] == 0 for b in st["buckets"].values())

    reg = obs.registry()
    assert reg.counter("dlaf_serve_drained_total",
                       op="cholesky").snapshot()["value"] == 3
    assert reg.counter("dlaf_serve_drained_total",
                       op="eigh").snapshot()["value"] == 2
    depth = [m for m in reg.snapshot()
             if m["name"] == "dlaf_serve_depth"]
    assert depth and all(m["value"] == 0.0 for m in depth)

    obs.flush()
    recs = [r for r in obs.read_records(path)
            if r.get("type") == "resilience" and r.get("event") == "drain"]
    assert len(recs) == 5
    assert all(r["site"] == "serve.queue" for r in recs)
    # records ↔ tickets joined by trace ID, one each, attrs name the rid
    assert ({r["trace_id"] for r in recs}
            == {t.trace_id for _, t in drained})
    by_trace = {r["trace_id"]: r for r in recs}
    for req, t in drained:
        attrs = by_trace[t.trace_id]["attrs"]
        assert attrs == {"rid": req.rid, "op": req.op, "bucket_n": 16}
    assert obs.validate_file(path) == []
    assert len({t.trace_id for _, t in drained}) == 5


# ---------------------------------------------------------------------------
# Records, accuracy, and --require-serve
# ---------------------------------------------------------------------------

def _drive_warm_queue(tmp_path, warm=True, accuracy=True):
    path = str(tmp_path / "serve.jsonl")
    C.initialize(C.Configuration(metrics_path=path, program_telemetry=True,
                                 accuracy="1" if accuracy else "0",
                                 log="off"))
    svc = ProgramService()
    q = Queue(svc, batch=3, deadline_s=1e9, buckets=(16,),
              clock=_FakeClock())
    reqs = [Request(op="cholesky", a=_hpd(12 + 2 * (i % 3), seed=i))
            for i in range(6)]
    if warm:
        q.warmup(reqs)
    for r in reqs:
        q.submit(r)
    q.flush()
    obs.flush()
    return path, svc, q


def test_warmed_queue_artifact_passes_require_serve(tmp_path):
    path, svc, q = _drive_warm_queue(tmp_path)
    assert svc.stats()["misses"] == 0 and svc.stats()["hit_rate"] == 1.0
    errors = obs.validate_file(path, require_serve=True)
    assert errors == []
    recs = obs.read_records(path)
    dispatches = [r for r in recs if r.get("type") == "serve"
                  and r.get("event") == "dispatch"]
    requests = [r for r in recs if r.get("type") == "serve"
                and r.get("event") == "request"]
    assert len(requests) == 6 and q.dispatches == len(dispatches) == 2
    assert all(r["cache"] == "hit" for r in dispatches)
    # per-request span records ride alongside the typed serve records
    spans = [r for r in recs if r.get("type") == "span"
             and r.get("name") == "serve.request"]
    assert len(spans) == 6
    # per-request accuracy records: site serve, finite budget, n = the
    # REQUEST's n (not the bucket ceiling)
    accs = [r for r in recs if r.get("type") == "accuracy"
            and r.get("site") == "serve"]
    assert len(accs) == 6
    assert {r["n"] for r in accs} == {12, 14, 16}
    assert all(r["bound_ratio"] < 1.0 for r in accs)


def test_queue_accuracy_records_for_every_op(tmp_path):
    """Per-request accuracy probes for all three ops (the vmapped
    residual programs see ONE lane each — pinned after the CI smoke
    caught batch-axis indexing in the solve/eigh bodies)."""
    path = str(tmp_path / "acc.jsonl")
    C.initialize(C.Configuration(metrics_path=path, accuracy="1",
                                 log="off"))
    svc = ProgramService()
    q = Queue(svc, batch=2, deadline_s=1e9, buckets=(16,),
              clock=_FakeClock())
    rng = np.random.default_rng(0)
    for i in range(2):
        q.submit(Request(op="cholesky", a=_hpd(12, seed=i)))
    for i in range(2):
        q.submit(Request(op="solve", a=_tri(12, seed=i), alpha=2.0,
                         b=rng.standard_normal((12, 3))))
    for i in range(2):
        q.submit(Request(op="eigh", a=_sym(12, seed=i)))
    q.flush()
    obs.flush()
    accs = [r for r in obs.read_records(path)
            if r.get("type") == "accuracy" and r.get("site") == "serve"]
    assert len(accs) == 6
    by_metric = {r["metric"] for r in accs}
    assert by_metric == {"cholesky_residual", "trsm_residual",
                         "eigen_residual"}
    assert all(r["bound_ratio"] < 1.0 for r in accs)


def test_unwarmed_queue_artifact_fails_require_serve(tmp_path):
    path, svc, _ = _drive_warm_queue(tmp_path, warm=False)
    assert svc.stats()["misses"] >= 1
    errors = obs.validate_file(path, require_serve=True)
    assert any("cache miss" in e for e in errors)


def test_evicted_bucket_recompile_fails_require_serve(tmp_path):
    """The CI evict drill's validator leg: a warm stream interrupted by
    an evict shows a miss dispatch + a twice-traced serve site, and
    --require-serve must reject the artifact."""
    path = str(tmp_path / "drill.jsonl")
    C.initialize(C.Configuration(metrics_path=path, program_telemetry=True,
                                 log="off"))
    svc = ProgramService()
    q = Queue(svc, batch=2, deadline_s=1e9, buckets=(16,),
              clock=_FakeClock())
    sample = [Request(op="cholesky", a=_hpd(12))]
    q.warmup(sample)
    (spec,) = q.warmup_specs(sample)
    q.submit(Request(op="cholesky", a=_hpd(12, seed=1)))
    q.submit(Request(op="cholesky", a=_hpd(12, seed=2)))
    assert svc.evict(spec)
    q.submit(Request(op="cholesky", a=_hpd(12, seed=3)))
    q.submit(Request(op="cholesky", a=_hpd(12, seed=4)))
    assert svc.stats()["misses"] == 1
    obs.flush()
    errors = obs.validate_file(path, require_serve=True)
    assert any("cache miss" in e for e in errors)
    assert any("retraced mid-stream" in e for e in errors)


def test_serve_record_schema_rejections():
    from dlaf_tpu.obs.sinks import validate_records

    def rec(**kw):
        base = {"v": 1, "type": "serve", "ts": 1.0}
        base.update(kw)
        return base

    good_d = rec(event="dispatch", op="cholesky", bucket_n=16, nrhs=0,
                 dtype="float64", lanes=2, batch=4, cache="hit",
                 dispatch_s=0.01)
    good_r = rec(event="request", op="cholesky", n=12, bucket_n=16,
                 dtype="float64", queue_s=0.0, total_s=0.01)
    assert validate_records([good_d, good_r]) == []
    assert validate_records([rec(event="nope")])
    assert validate_records([dict(good_d, cache="warm")])
    assert validate_records([dict(good_d, lanes=9)])       # > batch
    assert validate_records([dict(good_d, dispatch_s=float("nan"))])
    assert validate_records([dict(good_d, nrhs=-1)])
    bad_nrhs = dict(good_d)
    del bad_nrhs["nrhs"]
    assert validate_records([bad_nrhs])
    assert validate_records([dict(good_r, bucket_n=8)])    # < n
    bad = dict(good_r)
    del bad["total_s"]
    assert validate_records([bad])


def test_validator_cli_require_serve_flag(tmp_path):
    from dlaf_tpu.obs.validate import main

    path = str(tmp_path / "x.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"v": 1, "type": "log", "ts": 1.0,
                            "level": "info", "logger": "t", "msg": "m",
                            "fields": {}}) + "\n")
    assert main([path]) == 0
    assert main([path, "--require-serve"]) == 1
    assert main([path, "--require-serve", "--history"]) == 2


# ---------------------------------------------------------------------------
# robust_cholesky_batched: per-lane recovery
# ---------------------------------------------------------------------------

def test_robust_batched_all_clean_is_one_attempt():
    a = _hpd_batch(3, 12)
    res = health.robust_cholesky_batched("L", a)
    assert res.attempts == 1 and res.lane_attempts == (1, 1, 1)
    assert res.shifts == (0.0,) and res.infos[0] == (0, 0, 0)
    for i in range(3):
        fac = np.tril(np.asarray(res.out)[i])
        np.testing.assert_allclose(
            fac @ fac.T, np.tril(a[i]) + np.tril(a[i], -1).T, atol=1e-10)


def test_robust_batched_retries_only_failed_lanes(tmp_path):
    """The per-lane contract: clean lanes keep their attempt-0 factor
    BITWISE (they are never re-dispatched), failed lanes recover under
    a shift, and dlaf_retry_total is attributed per lane."""
    C.initialize(C.Configuration(metrics_path=str(tmp_path / "m.jsonl"),
                                 log="off"))
    svc = ProgramService()
    a = _hpd_batch(4, 12)
    a[1] = _hpd(12, seed=20, shift=-80.0)
    a[3] = _hpd(12, seed=21, shift=-80.0)
    plain, _ = cholesky_batched("L", a.copy(), with_info=True, service=svc)
    res = health.robust_cholesky_batched("L", a, service=svc)
    assert res.attempts >= 2
    assert res.lane_attempts[0] == 1 and res.lane_attempts[2] == 1
    assert res.lane_attempts[1] == res.lane_attempts[3] >= 2
    out = np.asarray(res.out)
    np.testing.assert_array_equal(out[0], np.asarray(plain)[0])
    np.testing.assert_array_equal(out[2], np.asarray(plain)[2])
    for i in (1, 3):
        fac = np.tril(out[i])
        shift = res.shifts[res.lane_attempts[i] - 1]
        target = np.tril(a[i]) + np.tril(a[i], -1).T + shift * np.eye(12)
        np.testing.assert_allclose(fac @ fac.T, target, atol=1e-8)
    for lane in (1, 3):
        snap = obs.registry().counter("dlaf_retry_total",
                                      algo="cholesky_batched",
                                      lane=lane).snapshot()
        assert snap["value"] >= 1, (lane, snap)
    snap0 = obs.registry().counter("dlaf_retry_total",
                                   algo="cholesky_batched",
                                   lane=0).snapshot()
    assert snap0["value"] == 0


def test_robust_batched_single_retry_dispatch_reuses_program():
    """One re-dispatch per attempt through the SAME bucket program: the
    retry must be a cache hit, never a second compile."""
    svc = ProgramService()
    a = _hpd_batch(3, 10)
    a[1] = _hpd(10, seed=30, shift=-50.0)
    health.robust_cholesky_batched("L", a, service=svc)
    st = svc.stats()
    assert st["compiles"] == 1 and st["misses"] == 1 and st["hits"] >= 1


def test_robust_batched_exhaustion_raises():
    a = np.stack([_hpd(8), _hpd(8, seed=40, shift=-30.0)])
    with pytest.raises(health.FactorizationError) as exc:
        health.robust_cholesky_batched("L", a, max_attempts=1)
    assert exc.value.attempts == 1 and exc.value.infos == (1,)


def test_robust_batched_argument_validation():
    a = _hpd_batch(2, 8)
    with pytest.raises(ValueError):
        health.robust_cholesky_batched("L", a, max_attempts=0)
    with pytest.raises(ValueError):
        health.robust_cholesky_batched("L", a, shift=0.0)
    with pytest.raises(ValueError):
        health.robust_cholesky_batched("L", a, shift_growth=1.0)
    with pytest.raises(ValueError):
        health.robust_cholesky_batched("L", _hpd(8))


# ---------------------------------------------------------------------------
# bench serve arm + gate leg (aux pins)
# ---------------------------------------------------------------------------

def test_serve_lines_never_take_cholesky_headline():
    """workload="serve" measures requests/s, not GFlop/s: it must never
    surface as the cholesky headline nor enter its history lookup."""
    import bench

    serve_line = {"variant": "serve", "platform": "cpu",
                  "dtype": "float64", "n": 64, "nb": 64, "gflops": 4000.0,
                  "t": 0.001, "ts": "2026-08-04T00:00:00",
                  "source": "bench.py", "workload": "serve",
                  "speedup": 10.0}
    assert bench.assemble_headline([serve_line], 4096, 256,
                                   hist_lookup=lambda **kw: None) is None
    chol = {"variant": "loop", "platform": "cpu", "dtype": "float64",
            "n": 4096, "nb": 256, "gflops": 8.0, "t": 1.0,
            "ts": "2026-08-04T00:00:00", "source": "bench.py"}
    head = bench.assemble_headline([serve_line, chol], 4096, 256,
                                   hist_lookup=lambda **kw: None)
    assert head["value"] == 8.0 and "serve" not in head["metric"]


def test_bench_gate_serve_speedup_leg():
    from bench_gate import run_gate

    hist = []
    mk = lambda speedup: {"variant": "serve", "platform": "cpu",
                          "dtype": "float64", "n": 64, "nb": 64,
                          "gflops": 4000.0, "t": 0.001, "ts": "t",
                          "source": "s", "workload": "serve",
                          "speedup": speedup}
    logs = []
    assert run_gate(hist, [mk(3.5)], tolerance=0.1, min_history=3,
                    best_k=3, log=logs.append) == 0
    assert run_gate(hist, [mk(2.2)], tolerance=0.1, min_history=3,
                    best_k=3, log=logs.append) == 1
    # best-of protocol: one slow pass does not trip a key whose best
    # measurement cleared the floor
    assert run_gate(hist, [mk(2.2), mk(3.1)], tolerance=0.1,
                    min_history=3, best_k=3, log=logs.append) == 0
    # a serve line without the field is not a ratio measurement
    no_field = {k: v for k, v in mk(0).items() if k != "speedup"}
    assert run_gate(hist, [no_field], tolerance=0.1, min_history=3,
                    best_k=3, log=logs.append) == 0
    # a non-serve workload never faces the floor
    other = dict(mk(0.5), workload="fpanel")
    assert run_gate(hist, [other], tolerance=0.1, min_history=3,
                    best_k=3, log=logs.append) == 0
    assert any("ISSUE-11" in line for line in logs)


def test_bench_history_path_env_redirects_append(tmp_path):
    """DLAF_BENCH_HISTORY_PATH redirects the durable history append —
    the CI serve bench run must never mutate the git-tracked baseline
    file with container-local numbers (review finding)."""
    import measure_common

    repo_hist = os.path.join(measure_common.repo_root(),
                             ".bench_history.jsonl")
    before = os.path.getsize(repo_hist)
    redirected = tmp_path / "hist.jsonl"
    os.environ["DLAF_BENCH_HISTORY_PATH"] = str(redirected)
    try:
        line = measure_common.append_history(
            "cpu", 64, 64, 100.0, 0.01, source="test", variant="serve",
            workload="serve", extra={"speedup": 5.0})
    finally:
        os.environ.pop("DLAF_BENCH_HISTORY_PATH", None)
    assert os.path.getsize(repo_hist) == before
    from dlaf_tpu.obs import read_history_records

    (rec,) = read_history_records(str(redirected))
    assert rec["gflops"] == 100.0 and rec["speedup"] == line["speedup"]


def test_committed_history_carries_gating_serve_line():
    """The committed .bench_history.jsonl must hold >= 1 serve line
    whose speedup clears the floor — that line keeps the ISSUE-11
    acceptance enforced in every CI --replay."""
    from dlaf_tpu.obs import read_history_records

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".bench_history.jsonl")
    serve_lines = [r for r in read_history_records(path)
                   if r.get("workload") == "serve"]
    assert serve_lines, "no committed serve history line"
    assert any(r.get("speedup", 0) >= 3.0 for r in serve_lines)


# ---------------------------------------------------------------------------
# graphcheck integration
# ---------------------------------------------------------------------------

def test_graphcheck_traces_serve_batched_programs():
    """The audited program matrix includes the serve bucket programs
    (built through the service's own builder), and they audit clean."""
    from dlaf_tpu.analysis import depgraph, graphcheck

    specs = [s for s in graphcheck.program_specs()
             if s.name.startswith("serve.")]
    names = {s.name for s in specs}
    assert {"serve.cholesky.batched.L", "serve.cholesky.batched.U",
            "serve.solve.batched.LLN", "serve.eigh.batched.L"} <= names
    with graphcheck.pinned_native_config():
        for spec in specs:
            fn, args = spec.build()
            jaxpr = depgraph.trace(fn, *args)
            findings = graphcheck.audit_jaxpr(spec.name, jaxpr)
            assert findings == [], (spec.name, findings)


def test_program_builder_shapes_match_spec():
    from dlaf_tpu.serve import program_builder

    spec = solve_spec(batch=3, n=10, nrhs=4, nb=10, dtype="float32",
                      side="R", donate=True)
    fn, args, donate = program_builder(spec)
    assert [tuple(a.shape) for a in args] == [(3, 10, 10), (3, 4, 10),
                                              (3,)]
    assert donate == (1,)
    spec2 = eigh_spec(batch=2, n=8, nb=8, dtype="float64")
    fn2, args2, donate2 = program_builder(spec2)
    assert [tuple(a.shape) for a in args2] == [(2, 8, 8)]
    assert donate2 == ()
    with pytest.raises(ValueError):
        from dlaf_tpu.serve.programs import ProgramSpec
        program_builder(ProgramSpec(op="lu", batch=1, n=4, nb=4,
                                    dtype="float64"))
