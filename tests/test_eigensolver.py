"""End-to-end eigensolver tests
(reference: test/unit/eigensolver/test_eigensolver.cpp,
test_gen_eigensolver.cpp): |A Q - Q Lambda| residuals, orthogonality,
scipy cross-checks, both uplos, real + complex, odd sizes.
"""

import numpy as np
import pytest
import scipy.linalg as sla

from dlaf_tpu.algorithms.permutations import permute
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import RankIndex2D, TileElementSize
from dlaf_tpu.eigensolver.back_transform import bt_band_to_tridiag, bt_reduction_to_band
from dlaf_tpu.eigensolver.band_to_tridiag import band_to_tridiag_numpy
from dlaf_tpu.eigensolver.eigensolver import eigensolver, gen_eigensolver
from dlaf_tpu.eigensolver.reduction_to_band import extract_band, reduction_to_band
from dlaf_tpu.eigensolver.tridiag_solver import tridiag_solver
from dlaf_tpu.matrix.matrix import Matrix


def herm(n, dtype, seed, pd=False):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        x = x + 1j * rng.standard_normal((n, n))
    if pd:
        return (x @ x.conj().T + n * np.eye(n)).astype(dtype)
    return ((x + x.conj().T) / 2).astype(dtype)


def M(a, nb):
    return Matrix.from_global(a, TileElementSize(nb, nb))


# -- back-transform building blocks ----------------------------------------

@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,b", [(16, 4), (13, 3)])
def test_bt_band_to_tridiag(n, b, dtype):
    """Eigenvectors of the band matrix via chase + bt must diagonalize it."""
    rng = np.random.default_rng(7)
    x = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        x = x + 1j * rng.standard_normal((n, n))
    a = ((x + x.conj().T) / 2)
    mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) <= b
    a = np.where(mask, a, 0).astype(dtype)
    np.fill_diagonal(a, np.real(np.diag(a)))
    band = np.zeros((b + 1, n), dtype=dtype)
    for r in range(b + 1):
        band[r, : n - r] = np.diagonal(a, -r)
    tri = band_to_tridiag_numpy(band, b)
    lam, z = tridiag_solver(tri.d, tri.e, b, use_device=False)
    q = np.asarray(bt_band_to_tridiag(tri, z))
    assert np.linalg.norm(a @ q - q * lam[None, :]) < 1e-10 * n
    assert np.linalg.norm(q.conj().T @ q - np.eye(n)) < 1e-11 * n


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("group", [0, 1, 3, 5])
def test_bt_b2t_impl_variants(dtype, group, monkeypatch):
    """The blocked compact-WY application (config bt_b2t_impl/bt_b2t_group)
    must reproduce the sweep-at-a-time scan on the same reflector set."""
    import dlaf_tpu.config as config

    n, b = 29, 4
    rng = np.random.default_rng(11)
    x = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        x = x + 1j * rng.standard_normal((n, n))
    a = ((x + x.conj().T) / 2)
    mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) <= b
    a = np.where(mask, a, 0).astype(dtype)
    np.fill_diagonal(a, np.real(np.diag(a)))
    band = np.zeros((b + 1, n), dtype=dtype)
    for r in range(b + 1):
        band[r, : n - r] = np.diagonal(a, -r)
    tri = band_to_tridiag_numpy(band, b)
    lam, z = tridiag_solver(tri.d, tri.e, b, use_device=False)
    try:
        monkeypatch.setenv("DLAF_BT_B2T_IMPL", "sweeps")
        config.initialize()
        q_scan = np.asarray(bt_band_to_tridiag(tri, z))
        monkeypatch.setenv("DLAF_BT_B2T_IMPL", "blocked")
        monkeypatch.setenv("DLAF_BT_B2T_GROUP", str(group))
        config.initialize()
        q_blk = np.asarray(bt_band_to_tridiag(tri, z))
    finally:
        monkeypatch.delenv("DLAF_BT_B2T_IMPL", raising=False)
        monkeypatch.delenv("DLAF_BT_B2T_GROUP", raising=False)
        config.initialize()
    np.testing.assert_allclose(q_blk, q_scan, atol=5e-13 * n)
    assert np.linalg.norm(a @ q_blk - q_blk * lam[None, :]) < 1e-10 * n


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_bt_reduction_to_band(dtype):
    """Band eigenvectors lifted through the reduction must diagonalize A."""
    n, nb = 16, 4
    a = herm(n, dtype, 3)
    red = reduction_to_band(M(a, nb))
    band = extract_band(red)
    tri = band_to_tridiag_numpy(band, nb)
    lam, z = tridiag_solver(tri.d, tri.e, nb, use_device=False)
    zb = bt_band_to_tridiag(tri, z)
    q = np.asarray(bt_reduction_to_band(red, zb))
    assert np.linalg.norm(a @ q - q * lam[None, :]) < 1e-10 * n
    assert np.linalg.norm(q.conj().T @ q - np.eye(n)) < 1e-11 * n


# -- distributed back-transforms (reference distributed overloads,
#    bt_reduction_to_band/api.h:18-23, bt_band_to_tridiag/api.h:21-22) ------

@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("grid_shape,src", [((2, 2), (0, 0)), ((2, 4), (1, 1)),
                                            ((4, 2), (1, 0))])
@pytest.mark.parametrize("n,nb", [(24, 4), (21, 4)])
def test_bt_reduction_to_band_distributed(n, nb, grid_shape, src, dtype, devices8):
    a = herm(n, dtype, n + grid_shape[0])
    rng = np.random.default_rng(n)
    c = rng.standard_normal((n, n)).astype(dtype)
    red_local = reduction_to_band(M(a, nb))
    q_local = np.asarray(bt_reduction_to_band(red_local, c))

    grid = Grid(*grid_shape)
    srk = RankIndex2D(src[0] % grid_shape[0], src[1] % grid_shape[1])
    red_dist = reduction_to_band(Matrix.from_global(a, TileElementSize(nb, nb),
                                                    grid=grid, source_rank=srk))
    cm = Matrix.from_global(c, TileElementSize(nb, nb), grid=grid, source_rank=srk)
    q_dist = bt_reduction_to_band(red_dist, cm)
    assert isinstance(q_dist, Matrix)
    np.testing.assert_allclose(q_dist.to_numpy(), q_local, atol=1e-12 * n)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("grid_shape,src", [((2, 2), (0, 0)), ((4, 2), (1, 1)),
                                            ((2, 4), (0, 1))])
@pytest.mark.parametrize("n,b", [(24, 4), (21, 4)])
def test_bt_band_to_tridiag_distributed(n, b, grid_shape, src, dtype, devices8):
    rng = np.random.default_rng(n + b)
    x = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        x = x + 1j * rng.standard_normal((n, n))
    a = ((x + x.conj().T) / 2)
    mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) <= b
    a = np.where(mask, a, 0).astype(dtype)
    band = np.zeros((b + 1, n), dtype=dtype)
    for r in range(b + 1):
        band[r, : n - r] = np.diagonal(a, -r)
    tri = band_to_tridiag_numpy(band, b)
    lam, z = tridiag_solver(tri.d, tri.e, b, use_device=False)
    q_local = np.asarray(bt_band_to_tridiag(tri, z))

    grid = Grid(*grid_shape)
    srk = RankIndex2D(src[0] % grid_shape[0], src[1] % grid_shape[1])
    zm = Matrix.from_global(np.asarray(z), TileElementSize(b, b), grid=grid,
                            source_rank=srk)
    q_dist = bt_band_to_tridiag(tri, zm)
    assert isinstance(q_dist, Matrix)
    np.testing.assert_allclose(q_dist.to_numpy(), q_local, atol=1e-12 * n)
    # and it must still diagonalize the band matrix
    q = q_dist.to_numpy()
    assert np.linalg.norm(a @ q - q * lam[None, :]) < 1e-10 * n


# -- full pipeline ----------------------------------------------------------

@pytest.mark.parametrize("dtype", [np.float64, np.complex128, np.float32])
@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("n,nb", [(16, 4), (24, 8), (13, 4), (4, 4), (33, 8)])
def test_eigensolver(n, nb, uplo, dtype):
    a = herm(n, dtype, n + nb)
    res = eigensolver(uplo, M(a, nb))
    lam, q = res.eigenvalues, res.eigenvectors.to_numpy()
    afull = np.tril(a) + np.tril(a, -1).conj().T if uplo == "L" \
        else np.triu(a) + np.triu(a, 1).conj().T
    np.fill_diagonal(afull, np.real(np.diag(afull)))
    eps = np.finfo(np.dtype(dtype).type(0).real.dtype).eps
    tol = 100 * n * eps * max(np.abs(lam).max(initial=1.0), 1.0)
    np.testing.assert_allclose(lam, np.linalg.eigvalsh(afull), atol=tol)
    assert np.linalg.norm(afull @ q - q * lam[None, :]) < tol * 10
    assert np.linalg.norm(q.conj().T @ q - np.eye(n)) < 100 * n * eps


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_eigensolver_under_mxu_knobs(dtype, monkeypatch):
    """Full pipeline with f64_gemm="mxu" + f64_trsm="mixed": every level-3
    tile op in reduction_to_band / back-transforms / D&C gemms goes through
    the int8 path (min_dim lowered to touch it at test sizes) — residuals
    must stay f64-grade."""
    monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
    monkeypatch.setenv("DLAF_F64_GEMM_MIN_DIM", "4")
    monkeypatch.setenv("DLAF_F64_TRSM", "mixed")
    import dlaf_tpu.config as config
    config.initialize()
    try:
        n, nb = 24, 8
        a = herm(n, dtype, 5)
        res = eigensolver("L", M(a, nb))
        lam, q = res.eigenvalues, res.eigenvectors.to_numpy()
        afull = np.tril(a) + np.tril(a, -1).conj().T
        np.fill_diagonal(afull, np.real(np.diag(afull)))
        np.testing.assert_allclose(lam, np.linalg.eigvalsh(afull), atol=1e-11 * n)
        assert np.linalg.norm(afull @ q - q * lam[None, :]) < 1e-10 * n
        assert np.linalg.norm(q.conj().T @ q - np.eye(n)) < 1e-11 * n
    finally:
        for v in ("DLAF_F64_GEMM", "DLAF_F64_GEMM_MIN_DIM", "DLAF_F64_TRSM"):
            monkeypatch.delenv(v)
        config.initialize()


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("grid_shape,src", [((2, 2), (0, 0)), ((2, 4), (1, 1))])
@pytest.mark.parametrize("n,nb", [(24, 4), (21, 4)])
def test_eigensolver_distributed(n, nb, grid_shape, src, dtype, devices8):
    """Beyond-parity: the full pipeline over a device grid (the reference's
    eigensolver is local-only, api.h:28-31)."""
    a = herm(n, dtype, n + nb)
    grid = Grid(*grid_shape)
    srk = RankIndex2D(src[0] % grid_shape[0], src[1] % grid_shape[1])
    am = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid, source_rank=srk)
    res = eigensolver("L", am)
    lam, q = res.eigenvalues, res.eigenvectors.to_numpy()
    afull = np.tril(a) + np.tril(a, -1).conj().T
    np.fill_diagonal(afull, np.real(np.diag(afull)))
    np.testing.assert_allclose(lam, np.linalg.eigvalsh(afull), atol=1e-10 * n)
    assert np.linalg.norm(afull @ q - q * lam[None, :]) < 1e-10 * n
    assert np.linalg.norm(q.conj().T @ q - np.eye(n)) < 1e-11 * n


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
def test_gen_eigensolver_distributed(dtype, devices8):
    n, nb = 24, 4
    a = herm(n, dtype, 21)
    b = herm(n, dtype, 22, pd=True)
    grid = Grid(2, 2)
    am = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid)
    bm = Matrix.from_global(b, TileElementSize(nb, nb), grid=grid)
    res = gen_eigensolver("L", am, bm)
    lam, q = res.eigenvalues, res.eigenvectors.to_numpy()
    w = sla.eigh(a, b, eigvals_only=True)
    np.testing.assert_allclose(lam, w, atol=1e-9)
    assert np.linalg.norm(a @ q - (b @ q) * lam[None, :]) < 1e-9 * n
    assert np.linalg.norm(q.conj().T @ b @ q - np.eye(n)) < 1e-10 * n


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,nb,band", [(32, 8, 4), (29, 8, 2), (24, 8, 4)])
def test_eigensolver_band_size(n, nb, band, dtype):
    """Full local pipeline at band < block size: every stage (extract_band,
    chase, both back-transforms) must consume the narrow-band layout."""
    a = herm(n, dtype, seed=n + 3 * band)
    res = eigensolver("L", M(a, nb), band_size=band)
    q = res.eigenvectors.to_numpy()
    lam = res.eigenvalues
    assert np.linalg.norm(a @ q - q * lam[None, :]) < 1e-10 * n
    assert np.linalg.norm(q.conj().T @ q - np.eye(n)) < 1e-11 * n
    np.testing.assert_allclose(np.sort(lam), np.sort(sla.eigvalsh(a)),
                               atol=1e-10 * n)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,nb,band,grid_shape,src",
                         [(32, 8, 4, (2, 2), (0, 0)),
                          (29, 8, 2, (2, 4), (1, 2)),
                          (24, 8, 4, (4, 2), (3, 1))])
def test_eigensolver_distributed_band_size(n, nb, band, grid_shape, src,
                                           dtype, devices8):
    """Distributed pipeline at band < block size (beyond-reference on both
    the forward reduction and bt_reduction_to_band)."""
    from dlaf_tpu.common.index2d import RankIndex2D

    a = herm(n, dtype, seed=n + band)
    grid = Grid(*grid_shape)
    mat = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid,
                             source_rank=RankIndex2D(src[0] % grid_shape[0],
                                                     src[1] % grid_shape[1]))
    res = eigensolver("L", mat, band_size=band)
    q = res.eigenvectors.to_numpy()
    lam = res.eigenvalues
    assert np.linalg.norm(a @ q - q * lam[None, :]) < 1e-10 * n
    assert np.linalg.norm(q.conj().T @ q - np.eye(n)) < 1e-11 * n
    np.testing.assert_allclose(np.sort(lam), np.sort(sla.eigvalsh(a)),
                               atol=1e-10 * n)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("uplo", ["L", "U"])
def test_gen_eigensolver(uplo, dtype):
    n, nb = 16, 4
    a = herm(n, dtype, 11)
    b = herm(n, dtype, 12, pd=True)
    res = gen_eigensolver(uplo, M(a, nb), M(b, nb))
    lam, q = res.eigenvalues, res.eigenvectors.to_numpy()
    w = sla.eigh(a, b, eigvals_only=True)
    np.testing.assert_allclose(lam, w, atol=1e-9)
    # generalized residual |A q - lam B q|
    resid = np.linalg.norm(a @ q - (b @ q) * lam[None, :])
    assert resid < 1e-9 * n
    # B-orthogonality
    assert np.linalg.norm(q.conj().T @ b @ q - np.eye(n)) < 1e-10 * n


def test_permutations():
    rng = np.random.default_rng(0)
    a = rng.standard_normal((16, 16))
    mat = M(a, 4)
    perm = rng.permutation(8)
    out = permute("Row", perm, mat, 1, 3).to_numpy()
    expect = a.copy()
    expect[4:12] = a[4:12][perm]
    np.testing.assert_array_equal(out, expect)
    out = permute("Col", perm, mat, 1, 3).to_numpy()
    expect = a.copy()
    expect[:, 4:12] = a[:, 4:12][:, perm]
    np.testing.assert_array_equal(out, expect)


@pytest.mark.parametrize("grid_shape", [(2, 4), (4, 2)])
@pytest.mark.parametrize("src", [RankIndex2D(0, 0), RankIndex2D(1, 1)])
def test_permutations_distributed(grid_shape, src, devices8):
    """Distributed Matrix-level permute (one all_gather of the affected
    slot window + static per-rank gather tables, no host densify —
    reference ``permutations/general/impl.h:40-155`` operates on local
    tiles; this is the grid-scalable form): must match the local-path
    result, source-rank offsets, partial and edge-clamped ranges
    included."""
    rng = np.random.default_rng(3)
    a = rng.standard_normal((21, 21))
    grid = Grid(*grid_shape)
    mat = Matrix.from_global(a, TileElementSize(4, 4), grid=grid,
                             source_rank=src)
    perm = rng.permutation(8)
    out = permute("Row", perm, mat, 1, 3).to_numpy()
    expect = a.copy()
    expect[4:12] = a[4:12][perm]
    np.testing.assert_array_equal(out, expect)
    permc = rng.permutation(9)   # tile_end=None: clamped at the edge (21)
    out = permute("Col", permc, mat, 3, None).to_numpy()
    expect = a.copy()
    expect[:, 12:21] = a[:, 12:21][:, permc]
    np.testing.assert_array_equal(out, expect)
    # non-square blocks: the two axes use distinct block sizes in the
    # gather tables and the storage reshape layouts
    rect = Matrix.from_global(a, TileElementSize(4, 8), grid=grid,
                              source_rank=src)
    out = permute("Row", perm, rect, 1, 3).to_numpy()
    expect = a.copy()
    expect[4:12] = a[4:12][perm]
    np.testing.assert_array_equal(out, expect)
    permc8 = rng.permutation(13)  # cols 8..21 with 8-wide blocks
    out = permute("Col", permc8, rect, 1, None).to_numpy()
    expect = a.copy()
    expect[:, 8:21] = a[:, 8:21][:, permc8]
    np.testing.assert_array_equal(out, expect)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,nb,band,grid_shape",
                         [(24, 4, 4, (2, 4)), (21, 4, 4, (4, 2)),
                          (22, 8, 4, (2, 2))])
def test_bt_reduction_to_band_distributed_scan(n, nb, band, grid_shape,
                                               dtype, devices8, monkeypatch):
    """dist_step_mode="scan" back-transform (traced reflector-block index,
    rolled sub-panels) must match the unrolled local result, sub-block
    bands included."""
    a = herm(n, dtype, n + band)
    rng = np.random.default_rng(n)
    c = rng.standard_normal((n, n)).astype(dtype)
    red_local = reduction_to_band(M(a, nb), band_size=band)
    q_local = np.asarray(bt_reduction_to_band(red_local, c))

    monkeypatch.setenv("DLAF_DIST_STEP_MODE", "scan")
    import dlaf_tpu.config as config

    config.initialize()
    try:
        grid = Grid(*grid_shape)
        red_dist = reduction_to_band(
            Matrix.from_global(a, TileElementSize(nb, nb), grid=grid),
            band_size=band)
        cm = Matrix.from_global(c, TileElementSize(nb, nb), grid=grid)
        q_dist = bt_reduction_to_band(red_dist, cm)
        np.testing.assert_allclose(q_dist.to_numpy(), q_local, atol=1e-12 * n)
    finally:
        monkeypatch.delenv("DLAF_DIST_STEP_MODE")
        config.initialize()


def test_eigensolver_complex_pair_transfer_mode(monkeypatch):
    """Forced complex pair-transfer mode (matrix/memory.py): the full
    complex local eigensolver — band gather, host chase, phase arrays,
    back-transforms — must work without any direct complex transfer."""
    from dlaf_tpu.matrix import memory

    n, nb = 24, 4
    a = herm(n, np.complex128, 9)
    lam_ref = np.linalg.eigvalsh(a)

    monkeypatch.setattr(memory, "_complex_pair_mode", True)
    res = eigensolver("L", M(a, nb))
    np.testing.assert_allclose(res.eigenvalues, lam_ref, atol=1e-9)
    q = res.eigenvectors.to_numpy()
    resid = np.linalg.norm(a @ q - q * res.eigenvalues[None, :])
    assert resid < 1e-9 * np.linalg.norm(a)
