"""Tests for the tagged index algebra (reference: test/unit/common/test_index2d.cpp)."""

import pytest

from dlaf_tpu.common.asserts import DlafAssertError
from dlaf_tpu.common.index2d import (GlobalElementIndex, GlobalElementSize, GlobalTileIndex,
                                     GlobalTileSize, LocalTileIndex, LocalTileSize, Ordering,
                                     compute_coords, compute_linear_index, iterate_range2d)


def test_basic_coords():
    i = GlobalElementIndex(3, 5)
    assert (i.row, i.col) == (3, 5)
    assert tuple(i) == (3, 5)
    assert i.transposed() == GlobalElementIndex(5, 3)
    assert str(i) == "(3, 5)"


def test_tag_safety():
    # indices of different tags never compare equal (dataclass eq checks type)
    assert GlobalTileIndex(1, 2) != LocalTileIndex(1, 2)
    assert GlobalTileIndex(1, 2) == GlobalTileIndex(1, 2)
    # is_in only accepts the paired size tag (reference compile error -> assert)
    with pytest.raises(DlafAssertError):
        GlobalTileIndex(0, 0).is_in(LocalTileSize(2, 2))


def test_is_in():
    sz = GlobalElementSize(4, 6)
    assert GlobalElementIndex(0, 0).is_in(sz)
    assert GlobalElementIndex(3, 5).is_in(sz)
    assert not GlobalElementIndex(4, 0).is_in(sz)
    assert not GlobalElementIndex(0, 6).is_in(sz)


def test_size_predicates():
    assert GlobalElementSize(0, 3).is_empty()
    assert not GlobalElementSize(2, 3).is_empty()
    assert GlobalElementSize(2, 3).linear_size() == 6


def test_linear_index_roundtrip():
    dims = GlobalTileSize(3, 4)
    seen_rm, seen_cm = set(), set()
    for r in range(3):
        for c in range(4):
            idx = GlobalTileIndex(r, c)
            lin_rm = compute_linear_index(Ordering.RowMajor, idx, dims)
            lin_cm = compute_linear_index(Ordering.ColMajor, idx, dims)
            assert compute_coords(Ordering.RowMajor, lin_rm, dims, GlobalTileIndex) == idx
            assert compute_coords(Ordering.ColMajor, lin_cm, dims, GlobalTileIndex) == idx
            seen_rm.add(lin_rm)
            seen_cm.add(lin_cm)
    assert seen_rm == set(range(12)) and seen_cm == set(range(12))


def test_iterate_range2d():
    # col-major order, matching reference common/range2d.h
    pts = list(iterate_range2d(LocalTileSize(2, 3)))
    assert pts[0] == LocalTileIndex(0, 0)
    assert pts[1] == LocalTileIndex(1, 0)
    assert len(pts) == 6
    sub = list(iterate_range2d(LocalTileIndex(1, 1), LocalTileIndex(3, 2)))
    assert sub == [LocalTileIndex(1, 1), LocalTileIndex(2, 1)]
