"""Slow-tier distributed tests at realistic tile counts (>= 8 tiles per
rank on the 2x4 mesh) — the regime where telescoped-scan segment windows,
slot alignment, and the blocked HEGST's deferred trailing solve actually
interact (VERDICT r3 item 6; reference analog: the 6-rank suites'
size/grid sweeps, ``test/unit/factorization/test_cholesky.cpp:41-74``).

The toy-size suites (n <= 32) sweep grids/offsets broadly; these pin a few
deep configurations: n=512 with nb=32 gives nt=16 -> 8x4 = 32 tiles per
rank, so every telescoped segment boundary (chunks of ceil(16/8)=2 panels)
falls inside live data.

Grid shapes/orderings are ROTATED across the suite instead of
cross-producted (ADVICE r5 item 1): every test/config runs under exactly
ONE of 2x4 row-major / 4x2 row-major / 2x4 col-major, assigned
round-robin at import time in source order (:func:`_next_grid`), so the
slow tier stays ~flat (21 deep tests, not 63) while all three shapes —
tall, wide, col-major fill — keep coverage somewhere in the suite (the
module-bottom assertion pins that all three were actually assigned). A
deep-tier slot-alignment or owner-mapping bug specific to one shape
still fails here rather than on silicon; it just fails in the one test
carrying that shape.

Marked ``slow`` — excluded from ``-m quick``; run with the full suite or
``-m slow``.
"""

import itertools

import numpy as np
import pytest
import scipy.linalg as sla

import dlaf_tpu.config as config
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.algorithms.gen_to_std import gen_to_std
from dlaf_tpu.algorithms.triangular import (triangular_multiply,
                                            triangular_solve)
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.eigensolver.reduction_to_band import reduction_to_band
from dlaf_tpu.matrix.matrix import Matrix

pytestmark = pytest.mark.slow

N, NB = 512, 32          # nt=16: 8 row x 4 col slots per rank on the 2x4

#: The three deep-tier grid shapes (reference analog: the 6-rank fixtures
#: sweep 3x2 row-major / 2x3 col-major / split-comm sets per test,
#: ``test/include/dlaf_test/comm_grids/grids_6_ranks.h:12-58``).
_GRIDS = {"2x4r": (2, 4, "row-major"),
          "4x2r": (4, 2, "row-major"),
          "2x4c": (2, 4, "col-major")}
_CYCLE = itertools.cycle(sorted(_GRIDS))
_ASSIGNED = []


def _next_grid() -> str:
    """Round-robin grid id, drawn once per test/config at import time
    (decorator evaluation order == source order, so the assignment is
    deterministic and independent of collection order)."""
    gid = next(_CYCLE)
    _ASSIGNED.append(gid)
    return gid


def rotated(values):
    """Pair each of a test's own param configs with the next grid id."""
    return [(*v, _next_grid()) if isinstance(v, tuple)
            else (v, _next_grid()) for v in values]


def _grid(gid: str, devices8) -> Grid:
    rows, cols, ordering = _GRIDS[gid]
    return Grid(rows, cols, ordering=ordering)


def hpd(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    return x @ x.T + n * np.eye(n)


def set_step_mode(monkeypatch, mode):
    monkeypatch.setenv("DLAF_DIST_STEP_MODE", mode)
    config.initialize()


@pytest.fixture(autouse=True)
def _restore_config():
    yield
    config.initialize()


@pytest.mark.parametrize("trailing,gid", rotated(["loop", "scan"]))
def test_cholesky_deep(trailing, gid, devices8, monkeypatch):
    """Distributed Cholesky (unrolled + telescoped scan) at 32 tiles/rank
    against scipy."""
    grid = _grid(gid, devices8)
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", trailing)
    config.initialize()
    a = hpd(N, seed=1)
    out = cholesky("L", Matrix.from_global(a, TileElementSize(NB, NB),
                                           grid=grid)).to_numpy()
    np.testing.assert_allclose(np.tril(out), sla.cholesky(a, lower=True),
                               atol=1e-8 * N)


@pytest.mark.parametrize("mode,combo,gid", rotated([
    (m, c) for m in ("unrolled", "scan")
    for c in (("L", "L", "N"), ("R", "U", "C"))]))
def test_triangular_solve_deep(mode, combo, gid, devices8, monkeypatch):
    """Forward (LLN) and backward (RUC) distributed solves, both step
    formulations, at 32 tiles/rank — exercises the telescoped windows'
    bottom- and top-sliced forms with live data at every boundary."""
    grid = _grid(gid, devices8)
    side, uplo, op = combo
    set_step_mode(monkeypatch, mode)
    rng = np.random.default_rng(2)
    a = np.tril(rng.standard_normal((N, N))) + N * np.eye(N)
    if uplo == "U":
        a = a.T
    b = rng.standard_normal((N, N))
    ts = TileElementSize(NB, NB)
    am = Matrix.from_global(a, ts, grid=grid)
    bm = Matrix.from_global(b, ts, grid=grid)
    x = triangular_solve(side, uplo, op, "N", 1.0, am, bm).to_numpy()
    opa = a.conj().T if op == "C" else a
    ref = (sla.solve_triangular(opa, b, lower=(uplo == "L") != (op == "C"))
           if side == "L" else
           sla.solve_triangular(opa.T, b.T,
                                lower=(uplo == "U") != (op == "C")).T)
    np.testing.assert_allclose(x, ref, atol=1e-9 * N)


@pytest.mark.parametrize("mode,combo,gid", rotated([
    (m, c) for m in ("unrolled", "scan")
    for c in (("L", "L", "N"), ("R", "L", "C"))]))
def test_triangular_multiply_deep(mode, combo, gid, devices8, monkeypatch):
    grid = _grid(gid, devices8)
    side, uplo, op = combo
    set_step_mode(monkeypatch, mode)
    rng = np.random.default_rng(3)
    a = np.tril(rng.standard_normal((N, N)))
    b = rng.standard_normal((N, N))
    ts = TileElementSize(NB, NB)
    am = Matrix.from_global(a, ts, grid=grid)
    bm = Matrix.from_global(b, ts, grid=grid)
    out = triangular_multiply(side, uplo, op, "N", 1.0, am, bm).to_numpy()
    opa = a.conj().T if op == "C" else a
    ref = opa @ b if side == "L" else b @ opa
    np.testing.assert_allclose(out, ref, atol=1e-10 * N)


@pytest.mark.parametrize("mode,gid", rotated(["unrolled", "scan"]))
def test_hegst_blocked_deep(mode, gid, devices8, monkeypatch):
    """Distributed HEGST at 32 tiles/rank: the blocked form's deferred
    trailing solves span many panel fan-ins at nt=16 (unrolled mode);
    scan mode exercises the twosolve reroute through the telescoped
    triangular solver."""
    grid = _grid(gid, devices8)
    set_step_mode(monkeypatch, mode)
    a = hpd(N, seed=4)
    bf = sla.cholesky(hpd(N, seed=5), lower=True)
    ts = TileElementSize(NB, NB)
    am = Matrix.from_global(a, ts, grid=grid)
    lm = Matrix.from_global(bf, ts, grid=grid)
    out = gen_to_std("L", am, lm).to_numpy()
    linv = sla.solve_triangular(bf, np.eye(N), lower=True)
    ref = linv @ a @ linv.conj().T
    np.testing.assert_allclose(np.tril(out), np.tril(ref), atol=1e-8 * N)


@pytest.mark.parametrize("mode,gid", rotated(["unrolled", "scan"]))
def test_red2band_deep(mode, gid, devices8, monkeypatch):
    """Distributed reduction to band (band < block size) at 8 tiles/rank
    with nb=64: the telescoped red2band segments cover live panels; must
    match the local reduction exactly (same reflector schedule)."""
    grid = _grid(gid, devices8)
    set_step_mode(monkeypatch, mode)
    nb, band = 64, 32
    rng = np.random.default_rng(6)
    x = rng.standard_normal((N, N))
    a = (x + x.T) / 2
    local = reduction_to_band(Matrix.from_global(a, TileElementSize(nb, nb)),
                              band_size=band)
    dist = reduction_to_band(
        Matrix.from_global(a, TileElementSize(nb, nb), grid=grid),
        band_size=band)
    np.testing.assert_allclose(dist.matrix.to_numpy(),
                               local.matrix.to_numpy(), atol=1e-10 * N)
    np.testing.assert_allclose(np.asarray(dist.taus),
                               np.asarray(local.taus), atol=1e-11 * N)


@pytest.mark.parametrize("gid", [_next_grid()])
def test_cholesky_deep_complex(gid, devices8, monkeypatch):
    """Complex128 distributed Cholesky at 32 tiles/rank, scan mode — the
    deep tier's one complex configuration (the toy suites sweep complex
    broadly; this pins the telescoped windows x complex tile-op
    interaction at realistic tile counts)."""
    grid = _grid(gid, devices8)
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "scan")
    config.initialize()
    rng = np.random.default_rng(10)
    x = rng.standard_normal((N, N)) + 1j * rng.standard_normal((N, N))
    a = x @ x.conj().T + N * np.eye(N)
    out = cholesky("L", Matrix.from_global(a, TileElementSize(NB, NB),
                                           grid=grid)).to_numpy()
    np.testing.assert_allclose(np.tril(out), sla.cholesky(a, lower=True),
                               atol=1e-8 * N)


@pytest.mark.parametrize("gid", [_next_grid()])
def test_bt_r2b_deep(gid, devices8, monkeypatch):
    """Distributed bt_reduction_to_band in scan mode at npan=31 (n=512,
    nb=64, band=16): the telescoped reverse-sweep windows take NONZERO
    slot offsets here (the toy suites' npan <= 8 yield one full-window
    segment), so the window-relative rolled-panel math is exercised with
    base > 0. Must match the local back-transform."""
    from dlaf_tpu.eigensolver.back_transform import bt_reduction_to_band

    grid = _grid(gid, devices8)
    set_step_mode(monkeypatch, "scan")
    nb, band = 64, 16
    rng = np.random.default_rng(9)
    x = rng.standard_normal((N, N))
    a = (x + x.T) / 2
    c = rng.standard_normal((N, N))
    red_local = reduction_to_band(Matrix.from_global(a,
                                                     TileElementSize(nb, nb)),
                                  band_size=band)
    q_local = np.asarray(bt_reduction_to_band(red_local, c))
    red_dist = reduction_to_band(
        Matrix.from_global(a, TileElementSize(nb, nb), grid=grid),
        band_size=band)
    cm = Matrix.from_global(c, TileElementSize(nb, nb), grid=grid)
    q_dist = bt_reduction_to_band(red_dist, cm).to_numpy()
    np.testing.assert_allclose(q_dist, q_local, atol=1e-10 * N)


@pytest.mark.parametrize("gid", [_next_grid()])
def test_eigensolver_deep(gid, devices8, monkeypatch):
    """Full distributed eigensolver pipeline at n=512, nb=64: residual
    and orthogonality at 8+ tiles/rank (scan step mode — the hardware
    configuration for large tile counts)."""
    from dlaf_tpu.eigensolver.eigensolver import eigensolver

    grid = _grid(gid, devices8)
    set_step_mode(monkeypatch, "scan")
    nb = 64
    rng = np.random.default_rng(7)
    x = rng.standard_normal((N, N))
    a = (x + x.T) / 2
    res = eigensolver("L", Matrix.from_global(a, TileElementSize(nb, nb),
                                              grid=grid))
    w = np.asarray(res.eigenvalues)
    q = res.eigenvectors.to_numpy()
    assert np.all(np.diff(w) >= 0)
    resid = np.linalg.norm(a @ q - q * w[None, :]) / np.linalg.norm(a)
    assert resid < 1e-12 * N
    assert np.linalg.norm(q.T @ q - np.eye(N)) < 1e-12 * N


@pytest.mark.parametrize("gid", [_next_grid()])
def test_eigensolver_deep_mxu_mixed(gid, devices8, monkeypatch):
    """The hardware-session knob configuration (f64_gemm=mxu,
    f64_trsm=mixed, scan step modes) at 8+ tiles/rank — the exact config
    the TPU session runs, validated deep on the CPU mesh so session
    minutes never discover an interaction bug. Uses the emulated-f64
    accuracy budget (the mxu path is f64-grade by construction; the
    mixed panels are Newton-refined)."""
    from dlaf_tpu.eigensolver.eigensolver import eigensolver

    grid = _grid(gid, devices8)
    set_step_mode(monkeypatch, "scan")
    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "scan")
    monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
    monkeypatch.setenv("DLAF_F64_TRSM", "mixed")
    config.initialize()
    nb = 64
    rng = np.random.default_rng(11)
    x = rng.standard_normal((N, N))
    a = (x + x.T) / 2
    res = eigensolver("L", Matrix.from_global(a, TileElementSize(nb, nb),
                                              grid=grid))
    w = np.asarray(res.eigenvalues)
    q = res.eigenvectors.to_numpy()
    resid = np.linalg.norm(a @ q - q * w[None, :]) / np.linalg.norm(a)
    assert resid < 1e-11 * N
    assert np.linalg.norm(q.T @ q - np.eye(N)) < 1e-11 * N


@pytest.mark.parametrize("gid", [_next_grid()])
def test_cholesky_deep_mxu_accum_scan(gid, devices8, monkeypatch):
    """Distributed Cholesky under the full TPU product route (mxu gemms,
    mixed panels, concat group sums) with ozaki_accum="scan" — the
    O(1)-live-partials schedule armed as the N=16384 OOM fix must
    reproduce the same factorization the "xla" schedule gives through
    the REAL distributed path (shard_map + contract + trsm_panel), not
    just the 2D tile ops the bitwise unit tests cover."""
    grid = _grid(gid, devices8)
    monkeypatch.setenv("DLAF_F64_GEMM", "mxu")
    monkeypatch.setenv("DLAF_F64_TRSM", "mixed")
    monkeypatch.setenv("DLAF_OZAKI_GROUP", "concat")
    a = hpd(N, seed=4)
    outs = {}
    for accum in ("xla", "scan"):
        monkeypatch.setenv("DLAF_OZAKI_ACCUM", accum)
        config.initialize()
        outs[accum] = np.tril(cholesky(
            "L", Matrix.from_global(a, TileElementSize(NB, NB),
                                    grid=grid)).to_numpy())
    # bit-identical schedules end to end
    assert outs["scan"].tobytes() == outs["xla"].tobytes()
    np.testing.assert_allclose(outs["scan"],
                               sla.cholesky(a, lower=True), atol=1e-8 * N)


@pytest.mark.parametrize("gid", [_next_grid()])
def test_slot_alignment_net_has_teeth(gid, devices8, monkeypatch):
    """Sabotage check (VERDICT r3 item 6): shift the telescoped segment
    windows one slot late (`uniform_slot_start + 1`) and assert the deep
    Cholesky result actually corrupts — proving these tests would catch a
    real off-by-one in the slot-window math, not just pass vacuously."""
    import importlib

    grid = _grid(gid, devices8)
    # the algorithms package re-exports the cholesky FUNCTION under the
    # submodule's name; import_module returns the module itself
    chol_mod = importlib.import_module("dlaf_tpu.algorithms.cholesky")

    monkeypatch.setenv("DLAF_CHOLESKY_TRAILING", "scan")
    config.initialize()
    a = hpd(N, seed=8)
    ts = TileElementSize(NB, NB)
    good = cholesky("L", Matrix.from_global(a, ts, grid=grid)).to_numpy()
    np.testing.assert_allclose(np.tril(good), sla.cholesky(a, lower=True),
                               atol=1e-8 * N)

    monkeypatch.setattr(chol_mod, "uniform_slot_start",
                        lambda k, p: k // p + 1)
    chol_mod._dist_cholesky_cached.cache_clear()
    try:
        bad = cholesky("L", Matrix.from_global(a, ts, grid=grid)).to_numpy()
        assert not np.allclose(np.tril(bad), sla.cholesky(a, lower=True),
                               atol=1e-8 * N), \
            "sabotaged slot windows produced a correct result — the deep " \
            "distributed tests have no teeth"
    finally:
        monkeypatch.undo()
        chol_mod._dist_cholesky_cached.cache_clear()


@pytest.mark.parametrize("gid", [_next_grid()])
def test_slot_alignment_net_has_teeth_triangular(gid, devices8, monkeypatch):
    """Same sabotage for the telescoped triangular solve's own
    uniform_slot_start binding (each builder imports the bound into its
    namespace, so the Cholesky check does not cover it)."""
    import importlib

    grid = _grid(gid, devices8)
    tri_mod = importlib.import_module("dlaf_tpu.algorithms.triangular")
    set_step_mode(monkeypatch, "scan")
    rng = np.random.default_rng(12)
    a = np.tril(rng.standard_normal((N, N))) + N * np.eye(N)
    b = rng.standard_normal((N, N))
    ts = TileElementSize(NB, NB)
    am = Matrix.from_global(a, ts, grid=grid)
    bm = Matrix.from_global(b, ts, grid=grid)
    good = triangular_solve("L", "L", "N", "N", 1.0, am, bm).to_numpy()
    ref = sla.solve_triangular(a, b, lower=True)
    np.testing.assert_allclose(good, ref, atol=1e-9 * N)

    monkeypatch.setattr(tri_mod, "uniform_slot_start",
                        lambda k, p: k // p + 1)
    tri_mod._dist_solve_cached.cache_clear()
    try:
        bad = triangular_solve("L", "L", "N", "N", 1.0, am, bm).to_numpy()
        assert not np.allclose(bad, ref, atol=1e-9 * N), \
            "sabotaged solve windows produced a correct result"
    finally:
        monkeypatch.undo()
        tri_mod._dist_solve_cached.cache_clear()


# coverage pin for the rotation itself: every one of the three deep grid
# shapes must have been assigned to at least one test above — if an edit
# drops below 3 configs or breaks the cycle, the import fails loudly
assert set(_ASSIGNED) == set(_GRIDS), sorted(set(_ASSIGNED))
assert len(_ASSIGNED) == 21, len(_ASSIGNED)
