"""Pallas kernel tests (interpret mode on the CPU mesh)."""

import numpy as np
import pytest

import jax.numpy as jnp

from dlaf_tpu.tile_ops.pallas_kernels import masked_trailing_update, supports_pallas_update


@pytest.mark.parametrize("R,C,nb", [(3, 2, 16), (2, 2, 8), (1, 1, 8)])
def test_masked_trailing_update(R, C, nb):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((R, C, nb, nb)).astype(np.float32)
    vr = rng.standard_normal((R, nb, nb)).astype(np.float32)
    vc = rng.standard_normal((C, nb, nb)).astype(np.float32)
    mode = rng.integers(0, 4, size=(R, C)).astype(np.int32)
    out = np.asarray(masked_trailing_update(
        jnp.asarray(a), jnp.asarray(vr), jnp.asarray(vc), jnp.asarray(mode),
        interpret=True))
    tril = np.tril(np.ones((nb, nb), dtype=bool))
    triu = np.triu(np.ones((nb, nb), dtype=bool))
    for r in range(R):
        for c in range(C):
            full = a[r, c] - vr[r] @ vc[c].T
            if mode[r, c] == 0:
                expect = a[r, c]
            elif mode[r, c] == 1:
                expect = full
            elif mode[r, c] == 2:
                expect = np.where(tril, full, a[r, c])
            else:
                expect = np.where(triu, full, a[r, c])
            np.testing.assert_allclose(out[r, c], expect, rtol=2e-5, atol=2e-5)


def test_gate(monkeypatch):
    monkeypatch.delenv("DLAF_FORCE_PALLAS_UPDATE", raising=False)
    assert supports_pallas_update(jnp.float32, "tpu")
    assert supports_pallas_update(jnp.bfloat16, "tpu")
    assert not supports_pallas_update(jnp.float64, "tpu")
    assert not supports_pallas_update(jnp.float32, "cpu")
    assert not supports_pallas_update(jnp.complex64, "tpu")


@pytest.mark.parametrize("dtype,rtol", [(np.float32, 2e-5), (jnp.bfloat16, 8e-2)])
@pytest.mark.parametrize("R,C,nb", [(3, 2, 16), (2, 2, 8)])
def test_masked_trailing_update_dtypes(R, C, nb, dtype, rtol):
    """bf16 exercises the f32-accumulate/cast-back round-trip, including
    untouched (mode 0 / masked upper-triangle) elements passing through."""
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.standard_normal((R, C, nb, nb)), dtype=dtype)
    vr = jnp.asarray(rng.standard_normal((R, nb, nb)), dtype=dtype)
    vc = jnp.asarray(rng.standard_normal((C, nb, nb)), dtype=dtype)
    mode = jnp.asarray(rng.integers(0, 3, size=(R, C)), dtype=jnp.int32)
    out = masked_trailing_update(a, vr, vc, mode, interpret=True)
    assert out.dtype == a.dtype
    af, vrf, vcf = (np.asarray(x, dtype=np.float32) for x in (a, vr, vc))
    tri = np.tril(np.ones((nb, nb), dtype=bool))
    m = np.asarray(mode)
    outf = np.asarray(out, dtype=np.float32)
    for r in range(R):
        for c in range(C):
            full = af[r, c] - vrf[r] @ vcf[c].T
            if m[r, c] == 0:
                expect = af[r, c]
            elif m[r, c] == 1:
                expect = full
            else:
                expect = np.where(tri, full, af[r, c])
            np.testing.assert_allclose(outf[r, c], expect, rtol=rtol, atol=rtol)
            if m[r, c] == 0:
                # pass-through must be bit-exact, not a cast round-trip error
                np.testing.assert_array_equal(np.asarray(out[r, c]),
                                              np.asarray(a[r, c]))


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_distributed_cholesky_pallas_branch(monkeypatch, devices8, uplo):
    """Force the Pallas integration branch of the distributed trailing
    update (mode construction + .set() wiring) off-TPU via
    DLAF_FORCE_PALLAS_UPDATE; kernel runs in interpret mode on CPU."""
    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.comm.grid import Grid
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix

    monkeypatch.setenv("DLAF_FORCE_PALLAS_UPDATE", "1")
    n, nb = 24, 4
    grid = Grid(2, 4)
    rng = np.random.default_rng(7)
    x = rng.standard_normal((n, n))
    a = (x @ x.T + n * np.eye(n)).astype(np.float32)
    mat = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid)
    out = cholesky(uplo, mat).to_numpy()
    eps = np.finfo(np.float32).eps
    if uplo == "L":
        f = np.tril(out)
        resid = np.linalg.norm(f @ f.T - a) / np.linalg.norm(a)
        np.testing.assert_array_equal(np.triu(out, 1), np.triu(a, 1))
    else:
        f = np.triu(out)
        resid = np.linalg.norm(f.T @ f - a) / np.linalg.norm(a)
        np.testing.assert_array_equal(np.tril(out, -1), np.tril(a, -1))
    assert resid < 60 * n * eps


def test_fold_dot_routes_bitwise_equal():
    """The bf16 in-kernel dot route must produce BIT-identical (hi, lo)
    pairs to the int8 route (7-bit slices are exact in bf16; f32
    accumulation exact to k <= K_MAX <= 2^12)."""
    import numpy as np

    import jax.numpy as jnp
    from dlaf_tpu.tile_ops.pallas_ozaki import (fused_slice_product,
                                                fused_slice_syrk,
                                                masked_slice_product)

    rng = np.random.default_rng(9)
    s, m, k = 4, 512, 256
    ia = jnp.asarray(rng.integers(-64, 65, (s, m, k)), jnp.int8)
    ib = jnp.asarray(rng.integers(-64, 65, (s, k, m)), jnp.int8)
    h1, l1 = fused_slice_product(ia, ib, interpret=True)
    h2, l2 = fused_slice_product(ia, ib, interpret=True, dot="bf16")
    assert np.asarray(h1).tobytes() == np.asarray(h2).tobytes()
    assert np.asarray(l1).tobytes() == np.asarray(l2).tobytes()

    h1, l1 = fused_slice_syrk(ia, interpret=True)
    h2, l2 = fused_slice_syrk(ia, interpret=True, dot="bf16")
    assert np.asarray(h1).tobytes() == np.asarray(h2).tobytes()
    assert np.asarray(l1).tobytes() == np.asarray(l2).tobytes()

    iat = jnp.asarray(rng.integers(-64, 65, (s, 2, k, k)), jnp.int8)
    mode = jnp.asarray(np.tril(np.ones((2, 2), np.int32)))
    h1, l1 = masked_slice_product(iat, iat, mode, interpret=True)
    h2, l2 = masked_slice_product(iat, iat, mode, interpret=True, dot="bf16")
    assert np.asarray(h1).tobytes() == np.asarray(h2).tobytes()
    assert np.asarray(l1).tobytes() == np.asarray(l2).tobytes()


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_scan_cholesky_oz_pallas_branch(monkeypatch, devices8, uplo):
    """trailing="scan" + f64_gemm=mxu + ozaki_impl=pallas: the predicated
    kernel's mode mask is data, so it predicates the MXU work inside the
    scanned step too — must match the plain scan result. A spy asserts
    the predicated kernel actually ran (the plain mxu fallback would
    produce the same numerics and hide a dead gate)."""
    from dlaf_tpu import config
    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.comm.grid import Grid
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix

    rng = np.random.default_rng(3)
    n, nb = 24, 4
    x = rng.standard_normal((n, n))
    a = x @ x.T + 2 * n * np.eye(n)
    for k, v in (("DLAF_CHOLESKY_TRAILING", "scan"), ("DLAF_F64_GEMM", "mxu"),
                 ("DLAF_F64_GEMM_MIN_DIM", "1"), ("DLAF_OZAKI_IMPL", "pallas")):
        monkeypatch.setenv(k, v)
    config.initialize()
    import importlib

    chol_mod = importlib.import_module("dlaf_tpu.algorithms.cholesky")
    calls = []
    real = chol_mod._masked_oz_update

    def spy(*args, **kw):
        calls.append(1)
        return real(*args, **kw)

    monkeypatch.setattr(chol_mod, "_masked_oz_update", spy)
    try:
        m = Matrix.from_global(a, TileElementSize(nb, nb), grid=Grid(2, 4))
        out = cholesky(uplo, m).to_numpy()
        assert calls, "predicated oz kernel was gated out of the scan path"
        if uplo == "L":
            f = np.tril(out)
            resid = np.linalg.norm(f @ f.T - a) / np.linalg.norm(a)
        else:
            f = np.triu(out)
            resid = np.linalg.norm(f.T @ f - a) / np.linalg.norm(a)
        assert resid < 1e-13
    finally:
        for k in ("DLAF_CHOLESKY_TRAILING", "DLAF_F64_GEMM",
                  "DLAF_F64_GEMM_MIN_DIM", "DLAF_OZAKI_IMPL"):
            monkeypatch.delenv(k)
        config.initialize()
