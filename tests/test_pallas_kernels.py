"""Pallas kernel tests (interpret mode on the CPU mesh)."""

import numpy as np
import pytest

import jax.numpy as jnp

from dlaf_tpu.tile_ops.pallas_kernels import masked_trailing_update, supports_pallas_update


@pytest.mark.parametrize("R,C,nb", [(3, 2, 16), (2, 2, 8), (1, 1, 8)])
def test_masked_trailing_update(R, C, nb):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((R, C, nb, nb)).astype(np.float32)
    vr = rng.standard_normal((R, nb, nb)).astype(np.float32)
    vc = rng.standard_normal((C, nb, nb)).astype(np.float32)
    mode = rng.integers(0, 3, size=(R, C)).astype(np.int32)
    out = np.asarray(masked_trailing_update(
        jnp.asarray(a), jnp.asarray(vr), jnp.asarray(vc), jnp.asarray(mode),
        interpret=True))
    tri = np.tril(np.ones((nb, nb), dtype=bool))
    for r in range(R):
        for c in range(C):
            full = a[r, c] - vr[r] @ vc[c].T
            if mode[r, c] == 0:
                expect = a[r, c]
            elif mode[r, c] == 1:
                expect = full
            else:
                expect = np.where(tri, full, a[r, c])
            np.testing.assert_allclose(out[r, c], expect, rtol=2e-5, atol=2e-5)


def test_gate():
    assert supports_pallas_update(jnp.float32, "tpu")
    assert supports_pallas_update(jnp.bfloat16, "tpu")
    assert not supports_pallas_update(jnp.float64, "tpu")
    assert not supports_pallas_update(jnp.float32, "cpu")
    assert not supports_pallas_update(jnp.complex64, "tpu")
