"""band_to_tridiag tests
(reference: test/unit/eigensolver/test_band_to_tridiag.cpp): eigenvalue
preservation vs scipy, reflector-storage reconstruction, complex phases.
"""

import numpy as np
import pytest
import scipy.linalg as sla

from dlaf_tpu.eigensolver.band_to_tridiag import band_to_tridiag_numpy


def random_band(n, b, dtype, seed):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        x = x + 1j * rng.standard_normal((n, n))
    a = (x + x.conj().T) / 2
    mask = np.abs(np.subtract.outer(np.arange(n), np.arange(n))) <= b
    a = np.where(mask, a, 0).astype(dtype)
    np.fill_diagonal(a, np.real(np.diag(a)))
    band = np.zeros((b + 1, n), dtype=dtype)
    for r in range(b + 1):
        band[r, : n - r] = np.diagonal(a, -r)
    return a, band


def reconstruct_q(res, n):
    """Q = H_1^H H_2^H ... (apply in reverse order to I)."""
    b = res.band
    q = np.eye(n, dtype=res.v.dtype)
    n_sweeps, n_steps, _ = res.v.shape
    for s in range(n_sweeps - 1, -1, -1):
        for t in range(n_steps - 1, -1, -1):
            tau = res.tau[s, t]
            if tau == 0:
                continue
            r0 = s + 1 + t * b
            seg = min(b, n - r0)
            v = res.v[s, t, :seg]
            # Q <- H^H Q on rows r0:r0+seg
            q[r0: r0 + seg] -= np.conj(tau) * np.outer(v, v.conj() @ q[r0: r0 + seg])
    return q


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,b", [(12, 2), (16, 4), (13, 4), (17, 3), (8, 8), (5, 1)])
def test_band_to_tridiag(n, b, dtype):
    a, band = random_band(n, b, dtype, n + b)
    res = band_to_tridiag_numpy(band, b)
    w_ref = np.linalg.eigvalsh(a)
    w_tri = sla.eigvalsh_tridiagonal(res.d, res.e) if n > 1 else res.d
    np.testing.assert_allclose(w_tri, w_ref, atol=1e-10)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,b", [(12, 3), (10, 2)])
def test_band_to_tridiag_reflectors(n, b, dtype):
    """Q^H A Q must equal the (phase-restored) tridiagonal."""
    a, band = random_band(n, b, dtype, 3)
    res = band_to_tridiag_numpy(band, b)
    q = reconstruct_q(res, n)
    np.testing.assert_allclose(q @ q.conj().T, np.eye(n), atol=1e-12)
    t_real = np.diag(res.d) + np.diag(res.e, 1) + np.diag(res.e, -1)
    t_complex = np.diag(res.phase) @ t_real.astype(res.v.dtype) @ np.diag(res.phase.conj())
    np.testing.assert_allclose(q.conj().T @ a @ q, t_complex, atol=1e-10)


def test_band_one_is_noop_tridiag():
    n = 9
    a, band = random_band(n, 1, np.float64, 5)
    res = band_to_tridiag_numpy(band, 1)
    np.testing.assert_allclose(res.d, np.diagonal(a), atol=1e-14)
    np.testing.assert_allclose(np.abs(res.e), np.abs(np.diagonal(a, -1)), atol=1e-14)


# -- native C++ twin --------------------------------------------------------

@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,b", [(16, 4), (13, 3), (30, 5)])
def test_native_matches_numpy(n, b, dtype):
    from dlaf_tpu.native import bindings

    a, band = random_band(n, b, dtype, n * b)
    ref = band_to_tridiag_numpy(band, b)
    nat = bindings.band_to_tridiag(band, b)
    np.testing.assert_allclose(nat.d, ref.d, atol=1e-12)
    np.testing.assert_allclose(nat.e, ref.e, atol=1e-12)
    np.testing.assert_allclose(nat.v, ref.v, atol=1e-12)
    np.testing.assert_allclose(nat.tau, ref.tau, atol=1e-12)
    np.testing.assert_allclose(nat.phase, ref.phase, atol=1e-12)


@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("n,b", [(64, 8), (61, 4), (96, 16), (40, 8)])
@pytest.mark.parametrize("nthreads", [2, 4])
def test_native_pipelined_threads_bitwise(n, b, nthreads, dtype):
    """The pipelined sweep workers (reference SweepWorker analog) must give
    BITWISE the single-thread result at any worker count: step windows of
    concurrent sweeps are disjoint, so no reduction order changes."""
    from dlaf_tpu.native import bindings

    try:
        bindings.get_lib()
    except Exception:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(n + nthreads)
    band = rng.standard_normal((b + 1, n))
    if np.dtype(dtype).kind == "c":
        band = band + 1j * rng.standard_normal((b + 1, n))
        band[0] = np.real(band[0])
    band = band.astype(dtype)
    seq = bindings.band_to_tridiag(band, b, nthreads=1)
    par = bindings.band_to_tridiag(band, b, nthreads=nthreads)
    np.testing.assert_array_equal(par.d, seq.d)
    np.testing.assert_array_equal(par.e, seq.e)
    np.testing.assert_array_equal(par.v, seq.v)
    np.testing.assert_array_equal(par.tau, seq.tau)
    np.testing.assert_array_equal(par.phase, seq.phase)
