"""Tests for ISSUE 8: accuracy telemetry — in-graph quality probes,
accuracy records + history, and the accuracy-regression gate.

Covers: the DLAF_ACCURACY knob end-to-end (stochastic probe vs exact
dense residual within its variance bound across dtype x uplo x {local,
2x2 dist}; "full" == exact; the "0" bitwise-passthrough contract on the
factor outputs), the estimator family (cholesky/trsm/hegst/eigen/
orthogonality), the ``accuracy`` record schema + ``--require-accuracy``
validator leg + CLI exit codes, the D&C per-level deflation records,
the shared kind-parameterized history reader, and
``scripts/accuracy_gate.py`` (budget/drift/nonfinite legs, replay,
injection drill).
"""

import json
import math
import os
import re
import subprocess
import sys

import numpy as np
import pytest

import dlaf_tpu.config as C
from dlaf_tpu import obs
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.common.index2d import TileElementSize
from dlaf_tpu.comm.grid import Grid
from dlaf_tpu.matrix.matrix import Matrix
from dlaf_tpu.obs import accuracy
from dlaf_tpu.obs.sinks import (append_history_line, read_history_records,
                                validate_records)

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts")
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def accuracy_reset():
    """Leave every test with the suite's default unobserved config."""
    yield
    for key in ("DLAF_METRICS_PATH", "DLAF_LOG", "DLAF_ACCURACY"):
        os.environ.pop(key, None)
    obs._reset_for_tests()
    C.finalize()
    C.initialize()


def _hpd(n, dtype=np.float64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, n))
    if np.dtype(dtype).kind == "c":
        x = x + 1j * rng.standard_normal((n, n))
    a = x @ x.conj().T + n * np.eye(n)
    return np.asarray(a, dtype=dtype)


def _perturbed_factor(uplo, mat, scale=1e-8, seed=3):
    """A factor with a deliberate O(scale) error, so the residual sits
    far above the probe's own rounding floor."""
    fac = cholesky(uplo, mat)
    rng = np.random.default_rng(seed)
    noise = rng.standard_normal(fac.storage.shape)
    return fac.with_storage(fac.storage + scale * noise.astype(
        np.asarray(fac.storage).dtype))


def _exact_cholesky_residual(uplo, a, fac):
    f = fac.to_numpy()
    t = np.tril(f) if uplo == "L" else np.triu(f)
    z = t @ t.conj().T if uplo == "L" else t.conj().T @ t
    return float(np.linalg.norm(z - a) / np.linalg.norm(a))


# ---------------------------------------------------------------------------
# estimator: probe vs exact (the variance-bound satellite)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", [np.float64, np.complex128])
@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("dist", [False, True])
def test_probe_within_variance_bound(dtype, uplo, dist):
    n, nb = 96, 32
    a = _hpd(n, dtype)
    grid = Grid(2, 2) if dist else None
    mat = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid)
    fac = _perturbed_factor(uplo, mat)
    exact = _exact_cholesky_residual(uplo, a, fac)
    assert exact > 1e-10          # perturbation dominates rounding
    est = accuracy.cholesky_residual(uplo, mat, fac, mode="1")
    # k=8 Hutchinson: relative std of the squared estimate <= sqrt(2/8);
    # the seeded estimate must sit within a factor of 4 of the truth
    assert exact / 4 < est < exact * 4, (est, exact)
    full = accuracy.cholesky_residual(uplo, mat, fac, mode="full")
    assert full == pytest.approx(exact, rel=1e-10)


@pytest.mark.parametrize("uplo", ["L", "U"])
def test_dist_matches_local(uplo):
    """The distributed estimate equals the single-chip estimate of the
    same factor to rounding (the cross-rank all_reduce reassociates the
    partial sums — the documented exception to bitwise, docs/accuracy.md)
    and is itself bitwise-reproducible call to call."""
    n, nb = 96, 16
    a = _hpd(n)
    lmat = Matrix.from_global(a, TileElementSize(nb, nb))
    lfac = _perturbed_factor(uplo, lmat)
    dmat = Matrix.from_global(a, TileElementSize(nb, nb), grid=Grid(2, 2))
    dfac = dmat.with_storage(
        Matrix.from_global(lfac.to_numpy(), TileElementSize(nb, nb),
                           grid=Grid(2, 2)).storage)
    for mode in ("1", "full"):
        lv = accuracy.cholesky_residual(uplo, lmat, lfac, mode=mode)
        dv = accuracy.cholesky_residual(uplo, dmat, dfac, mode=mode)
        assert dv == pytest.approx(lv, rel=1e-10), (mode, lv, dv)
        # determinism: the same distributed program on the same data
        # returns the identical float (fixed probe seed + reduction shape)
        assert accuracy.cholesky_residual(uplo, dmat, dfac, mode=mode) == dv


# ---------------------------------------------------------------------------
# DLAF_ACCURACY=0 bitwise passthrough (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dist", [False, True])
def test_accuracy_knob_is_bitwise_passthrough(dist):
    """Factor outputs are identical with the knob off and on (probes are
    separate programs over the outputs, never fused into the
    factorization) — local and distributed."""
    n, nb = 64, 16
    a = _hpd(n)
    grid = Grid(2, 2) if dist else None

    def factor():
        mat = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid)
        return mat, cholesky("L", mat)

    os.environ["DLAF_ACCURACY"] = "0"
    C.initialize()
    _, f0 = factor()
    bytes0 = np.asarray(f0.storage).tobytes()
    os.environ["DLAF_ACCURACY"] = "1"
    C.initialize()
    mat1, f1 = factor()
    # run the probe too: computing it must not perturb anything
    value = accuracy.cholesky_residual("L", mat1, f1)
    assert math.isfinite(value)
    assert np.asarray(f1.storage).tobytes() == bytes0


# ---------------------------------------------------------------------------
# estimator family: trsm / hegst / eigen / orthogonality
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("side,uplo,op,diag", [
    ("L", "L", "N", "N"), ("L", "U", "C", "U"),
    ("R", "L", "T", "N"), ("R", "U", "N", "U")])
@pytest.mark.parametrize("dist", [False, True])
def test_trsm_estimator(side, uplo, op, diag, dist):
    from dlaf_tpu.algorithms.triangular import triangular_solve

    m, n, nb = 64, 32, 16
    adim = m if side == "L" else n
    rng = np.random.default_rng(1)
    # small off-diagonal + dominant diagonal: well-conditioned for BOTH
    # diag modes (diag="U" replaces the stored diagonal with ones, so a
    # large off-diagonal would make the unit-triangular system
    # exponentially ill-conditioned and the residual itself noisy)
    a = rng.standard_normal((adim, adim)) * (0.5 / adim) + 2.0 * np.eye(adim)
    b = rng.standard_normal((m, n))
    grid = Grid(2, 2) if dist else None
    am = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid)
    bm = Matrix.from_global(b, TileElementSize(nb, nb), grid=grid)
    out = triangular_solve(side, uplo, op, diag, 1.0, am, bm)
    t = np.tril(a) if uplo == "L" else np.triu(a)
    if diag == "U":
        np.fill_diagonal(t, 1.0)
    t = {"N": t, "T": t.T, "C": t.conj().T}[op]
    x = out.to_numpy()
    exact = np.linalg.norm((t @ x if side == "L" else x @ t) - b) \
        / np.linalg.norm(b)
    full = accuracy.trsm_residual(side, uplo, op, diag, 1.0, am, bm, out,
                                  mode="full")
    assert full == pytest.approx(exact, rel=1e-6, abs=1e-14)
    est = accuracy.trsm_residual(side, uplo, op, diag, 1.0, am, bm, out,
                                 mode="1")
    assert math.isfinite(est) and est < 1e-12   # solved system: tiny


@pytest.mark.parametrize("uplo", ["L", "U"])
@pytest.mark.parametrize("dist", [False, True])
def test_hegst_estimator(uplo, dist):
    from dlaf_tpu.algorithms.gen_to_std import gen_to_std

    n, nb = 64, 16
    a = _hpd(n, seed=5)
    bmat = _hpd(n, seed=6)
    grid = Grid(2, 2) if dist else None
    am = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid)
    bf = cholesky(uplo, Matrix.from_global(bmat, TileElementSize(nb, nb),
                                           grid=grid))
    out = gen_to_std(uplo, am, bf)
    f = bf.to_numpy()
    c = out.to_numpy()
    if uplo == "L":
        t = np.tril(f)
        ch = np.tril(c) + np.tril(c, -1).conj().T
        z = t @ ch @ t.conj().T
    else:
        t = np.triu(f)
        ch = np.triu(c) + np.triu(c, 1).conj().T
        z = t.conj().T @ ch @ t
    exact = np.linalg.norm(z - a) / np.linalg.norm(a)
    full = accuracy.hegst_residual(uplo, am, bf, out, mode="full")
    assert full == pytest.approx(exact, rel=1e-8, abs=1e-14)
    est = accuracy.hegst_residual(uplo, am, bf, out, mode="1")
    assert math.isfinite(est) and est < 1e-12


@pytest.mark.parametrize("dist", [False, True])
def test_eigen_estimators(dist):
    n, nb = 64, 16
    a = _hpd(n, seed=7)
    lam, z = np.linalg.eigh(a)
    # perturb Z so every metric sits far above its rounding floor — a
    # spuriously-zero estimator leg cannot hide under an abs tolerance
    rng = np.random.default_rng(8)
    z = z + 1e-7 * rng.standard_normal((n, n))
    grid = Grid(2, 2) if dist else None
    am = Matrix.from_global(a, TileElementSize(nb, nb), grid=grid)
    zm = Matrix.from_global(z, TileElementSize(nb, nb), grid=grid)
    full = accuracy.eigen_residuals("L", am, lam, zm, mode="full")
    exact = np.linalg.norm(a @ z - z * lam[None, :]) / np.linalg.norm(a)
    assert exact > 1e-9
    assert full["eigen_residual"] == pytest.approx(exact, rel=1e-8)
    exact_orth = np.linalg.norm(z.conj().T @ z - np.eye(n))
    assert full["orthogonality"] == pytest.approx(exact_orth, rel=1e-8)
    cols = np.linalg.norm(a @ z - z * lam[None, :], axis=0)
    exact_max = cols.max() / np.linalg.norm(a)
    assert full["eigenpair_max"] == pytest.approx(exact_max, rel=1e-8)
    est = accuracy.eigen_residuals("L", am, lam, zm, mode="1")
    assert exact / 4 < est["eigen_residual"] < exact * 4
    assert exact_orth / 4 < est["orthogonality"] < exact_orth * 4
    # the sampled max is a lower bound on the true max (subset of pairs)
    assert 0 < est["eigenpair_max"] <= exact_max * (1 + 1e-8)


def test_zero_reference_f32_guard():
    """An all-zero float32 reference must estimate 0.0, not NaN: the
    zero-denominator guard has to be representable in the computation
    dtype (a fixed 1e-300 rounds to 0.0f and 0/0 would NaN — flagging an
    uncorrupted run as corrupted)."""
    z = Matrix.from_global(np.zeros((32, 32), np.float32),
                           TileElementSize(16, 16))
    for mode in ("1", "full"):
        assert accuracy.cholesky_residual("L", z, z, mode=mode) == 0.0


def test_array_orthogonality():
    rng = np.random.default_rng(11)
    q, _ = np.linalg.qr(rng.standard_normal((48, 48)))
    assert accuracy.array_orthogonality(q, mode="full") < 1e-13
    exact = np.linalg.norm((2 * q).T @ (2 * q) - np.eye(48))
    assert accuracy.array_orthogonality(2 * q, mode="full") == \
        pytest.approx(exact, rel=1e-10)
    est = accuracy.array_orthogonality(2 * q, mode="1")
    assert exact / 4 < est < exact * 4


# ---------------------------------------------------------------------------
# records, schema, validator
# ---------------------------------------------------------------------------

def _arm(tmp_path, mode="1"):
    path = str(tmp_path / "acc.jsonl")
    C.initialize(C.Configuration(metrics_path=path, accuracy=mode))
    return path


def test_emit_record_and_gauge(tmp_path):
    path = _arm(tmp_path)
    res = accuracy.emit("site_x", "metric_y", 1.5e-15, n=128, nb=32,
                        c=60.0, dtype=np.float64, attrs={"uplo": "L"})
    assert res.passed and res.bound_ratio == pytest.approx(
        1.5e-15 / res.tol)
    obs.flush()
    recs = obs.read_records(path)
    acc = [r for r in recs if r.get("type") == "accuracy"]
    assert len(acc) == 1
    r = acc[0]
    assert r["site"] == "site_x" and r["metric"] == "metric_y"
    assert r["value"] == 1.5e-15 and r["n"] == 128 and r["nb"] == 32
    assert r["dtype"] == "float64" and r["platform"]
    assert r["attrs"]["uplo"] == "L" and r["attrs"]["mode"] == "1"
    assert math.isfinite(r["bound_ratio"]) and r["c"] == 60.0
    assert not validate_records(recs, require_accuracy=True)
    g = obs.registry().gauge("dlaf_accuracy_ratio", site="site_x",
                             metric="metric_y").snapshot()
    assert g["value"] == pytest.approx(res.bound_ratio)


def test_emit_nonfinite_record(tmp_path):
    path = _arm(tmp_path)
    res = accuracy.emit("site_x", "metric_y", float("nan"), n=64, nb=16,
                        c=60.0, dtype=np.float64)
    assert not res.passed and not res.finite and res.bound_ratio is None
    obs.flush()
    recs = obs.read_records(path)
    r = [x for x in recs if x.get("type") == "accuracy"][0]
    assert r["value"] is None and r["nonfinite"] is True
    assert "bound_ratio" not in r
    # schema-valid, but does NOT satisfy --require-accuracy
    assert not validate_records(recs)
    assert validate_records(recs, require_accuracy=True)
    cnt = obs.registry().counter("dlaf_accuracy_nonfinite_total",
                                 site="site_x", metric="metric_y").snapshot()
    assert cnt["value"] == 1


def test_emit_informational_metric(tmp_path):
    """c=None (e.g. the deflation fraction): no bound_ratio, no gauge,
    schema-valid, but not --require-accuracy evidence."""
    path = _arm(tmp_path)
    res = accuracy.emit("tridiag_solver", "dc_deflation_fraction", 0.5,
                        n=256, nb=32, c=None, dtype=np.float64,
                        attrs={"level": 1})
    assert res.passed and res.tol is None and res.bound_ratio is None
    obs.flush()
    recs = obs.read_records(path)
    r = [x for x in recs if x.get("type") == "accuracy"][0]
    assert "bound_ratio" not in r and "c" not in r
    assert not validate_records(recs)
    assert validate_records(recs, require_accuracy=True)


def test_accuracy_schema_rejections():
    base = {"type": "accuracy", "v": 1, "ts": 1.0, "site": "s",
            "metric": "m", "platform": "cpu", "n": 64, "nb": 16,
            "dtype": "float64", "value": 1e-15, "bound_ratio": 1e-3,
            "attrs": {}}
    assert not validate_records([dict(base)])
    assert validate_records([dict(base, value=float("nan"))])
    assert validate_records([dict(base, value=None)])          # no nonfinite
    assert validate_records([dict(base, value=None, nonfinite=True)])
    ok_nonfinite = dict(base, value=None, nonfinite=True)
    ok_nonfinite.pop("bound_ratio")
    assert not validate_records([ok_nonfinite])
    assert validate_records([dict(base, site="")])
    assert validate_records([dict(base, n=-1)])
    assert validate_records([dict(base, bound_ratio=float("inf"))])
    assert validate_records([dict(base, attrs="nope")])


def test_validator_cli_exit_codes(tmp_path):
    """Exit codes pinned like PR 7's: 2 for usage errors (unknown flag,
    incompatible modes), 1 for an empty artifact under the new
    requirement, 0 for a valid accuracy history."""
    art = tmp_path / "a.jsonl"
    art.write_text("")
    env = dict(os.environ, PYTHONPATH=REPO)

    def run(*args):
        return subprocess.run(
            [sys.executable, "-m", "dlaf_tpu.obs.validate", *args],
            capture_output=True, env=env, cwd=REPO).returncode

    assert run(str(art), "--require-accuracy") == 1
    assert run(str(art), "--no-such-flag") == 2
    assert run(str(art), "--history", "--require-accuracy") == 2
    assert run(str(art), "--accuracy-history", "--require-accuracy") == 2
    assert run(str(art), "--history", "--accuracy-history") == 2
    hist = tmp_path / "h.jsonl"
    hist.write_text(json.dumps(
        {"site": "s", "metric": "m", "platform": "cpu", "dtype": "float64",
         "n": 64, "nb": 16, "value": 1e-15, "bound_ratio": 1e-3,
         "ts": "t", "source": "test"}) + "\n")
    assert run(str(hist), "--accuracy-history") == 0
    assert run(str(hist), "--history") == 1      # wrong kind must fail


# ---------------------------------------------------------------------------
# shared history reader (satellite: one validating reader, no second parser)
# ---------------------------------------------------------------------------

def test_history_reader_kinds(tmp_path):
    path = str(tmp_path / "h.jsonl")
    line = {"site": "s", "metric": "m", "platform": "cpu",
            "dtype": "float64", "n": 64, "nb": 16, "value": 1e-15,
            "bound_ratio": 1e-3, "ts": "t", "source": "test"}
    append_history_line(path, line, kind="accuracy")
    assert read_history_records(path, kind="accuracy") == [line]
    with pytest.raises(ValueError):
        append_history_line(path, dict(line, bound_ratio=float("nan")),
                            kind="accuracy")
    with pytest.raises(ValueError):
        append_history_line(path, dict(line, site=""), kind="accuracy")
    # a bench line is NOT a valid accuracy line and vice versa — the one
    # reader, parameterized, keeps the two schemas honest
    bench = {"variant": "ozaki", "platform": "tpu", "dtype": "float64",
             "n": 4096, "nb": 256, "gflops": 100.0, "t": 1.0,
             "ts": "t", "source": "test"}
    with pytest.raises(ValueError):
        append_history_line(path, bench, kind="accuracy")
    bpath = str(tmp_path / "b.jsonl")
    append_history_line(bpath, bench)            # default kind: bench
    assert read_history_records(bpath) == [bench]
    with pytest.raises(ValueError):
        read_history_records(bpath, kind="accuracy")


def test_gates_share_one_reader():
    """Both gate scripts read history through obs.sinks'
    read_history_records — neither carries a bespoke parser."""
    import accuracy_gate
    import bench_gate

    assert bench_gate.read_history_records is read_history_records
    assert accuracy_gate.read_history_records is read_history_records


# ---------------------------------------------------------------------------
# D&C deflation records
# ---------------------------------------------------------------------------

def test_deflation_records(tmp_path):
    from dlaf_tpu.eigensolver.tridiag_solver import tridiag_solver

    path = _arm(tmp_path)
    rng = np.random.default_rng(2)
    n = 96
    tridiag_solver(rng.standard_normal(n), rng.standard_normal(n - 1), 16)
    obs.flush()
    recs = obs.read_records(path)
    defl = [r for r in recs if r.get("type") == "accuracy"
            and r.get("metric") == "dc_deflation_fraction"]
    assert defl, "no deflation records emitted"
    assert not validate_records(recs)
    levels = set()
    for r in defl:
        assert r["site"] == "tridiag_solver"
        assert 0.0 <= r["value"] <= 1.0
        assert r["attrs"]["merges"] >= 1
        assert r["attrs"]["deflated_poles"] <= r["attrs"]["merged_poles"]
        levels.add(r["attrs"]["level"])
    assert len(levels) == len(defl)     # one record per tree level


def test_deflation_off_by_default(tmp_path):
    from dlaf_tpu.eigensolver.tridiag_solver import tridiag_solver

    path = str(tmp_path / "acc.jsonl")
    C.initialize(C.Configuration(metrics_path=path))     # accuracy="0"
    rng = np.random.default_rng(2)
    tridiag_solver(rng.standard_normal(64), rng.standard_normal(63), 16)
    obs.flush()
    recs = obs.read_records(path)
    assert not any(r.get("type") == "accuracy" for r in recs)


# ---------------------------------------------------------------------------
# miniapp integration: stdout contract + artifact records
# ---------------------------------------------------------------------------

CHECK_RE = re.compile(
    r"^check: (PASSED|FAILED) residual=\d\.\d{3}e[+-]\d+ "
    r"tol=\d\.\d{3}e[+-]\d+( \[.*\])?$")


def _arm_env(tmp_path):
    """Arm via env (miniapp run() re-initializes config from env/CLI, so
    a user-struct metrics_path would be dropped)."""
    path = str(tmp_path / "acc.jsonl")
    os.environ["DLAF_METRICS_PATH"] = path
    os.environ["DLAF_ACCURACY"] = "1"
    C.initialize()
    return path


def test_miniapp_check_stdout_contract(tmp_path, capsys):
    """The `check:` line format is bit-for-bit the historical contract
    (existing CI greps key on it), now fed by the device estimator."""
    from dlaf_tpu.miniapp import miniapp_cholesky

    path = _arm_env(tmp_path)
    miniapp_cholesky.run(["-m", "64", "-b", "16", "--nruns", "1",
                          "--check-result", "last"])
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if ln.startswith("check:")]
    assert len(lines) == 1 and CHECK_RE.match(lines[0]), lines
    assert "PASSED" in lines[0]
    obs.flush()
    recs = obs.read_records(path)
    assert not validate_records(recs, require_accuracy=True)
    acc = [r for r in recs if r.get("type") == "accuracy"]
    # exactly ONE record for the checked run: the check emits it, and
    # the timed-run emission skips (no double probe / duplicate rows)
    assert len(acc) == 1
    assert acc[0]["attrs"].get("check") is True


def test_miniapp_check_distributed(capsys):
    from dlaf_tpu.miniapp import miniapp_cholesky

    miniapp_cholesky.run(["-m", "64", "-b", "16", "--grid-rows", "2",
                          "--grid-cols", "2", "--nruns", "1",
                          "--check-result", "last"])
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("check:")]
    assert len(lines) == 1 and "PASSED" in lines[0]


def test_miniapp_trsm_and_hegst_checks(capsys):
    from dlaf_tpu.miniapp import (miniapp_gen_to_std,
                                  miniapp_triangular_solver)

    miniapp_triangular_solver.run(["-m", "64", "-n", "32", "-b", "16",
                                   "--check-result", "last"])
    miniapp_gen_to_std.run(["-m", "64", "-b", "16",
                            "--check-result", "last"])
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("check:")]
    assert len(lines) == 2
    for ln in lines:
        assert CHECK_RE.match(ln) and "PASSED" in ln, ln


def test_miniapp_eigensolver_check(tmp_path, capsys):
    from dlaf_tpu.miniapp import miniapp_eigensolver

    path = _arm_env(tmp_path)
    miniapp_eigensolver.run(["-m", "64", "-b", "16", "--nruns", "1",
                             "--check-result", "last"])
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("check:")]
    assert len(lines) == 1 and CHECK_RE.match(lines[0]) \
        and "PASSED" in lines[0]
    obs.flush()
    recs = obs.read_records(path)
    metrics = {r["metric"] for r in recs if r.get("type") == "accuracy"}
    assert {"eigen_residual", "eigenpair_max", "orthogonality",
            "dc_deflation_fraction"} <= metrics


# ---------------------------------------------------------------------------
# accuracy gate
# ---------------------------------------------------------------------------

def _hist_line(ratio, **over):
    line = {"site": "s", "metric": "m", "platform": "cpu",
            "dtype": "float64", "n": 64, "nb": 16, "value": ratio * 1e-12,
            "bound_ratio": ratio, "ts": "t", "source": "test"}
    line.update(over)
    return line


def test_gate_legs():
    from accuracy_gate import run_gate

    hist = [_hist_line(0.001), _hist_line(0.0012), _hist_line(0.0008)]
    logs = []
    # clean: within budget and drift
    assert run_gate(hist, [_hist_line(0.002)], budget=1.0, drift=4.0,
                    min_history=3, log=logs.append) == 0
    # drift trip: 10x the median
    assert run_gate(hist, [_hist_line(0.01)], budget=1.0, drift=4.0,
                    min_history=3, log=logs.append) == 1
    # budget trip, even with no history for the key
    assert run_gate([], [_hist_line(1.5)], budget=1.0, drift=4.0,
                    min_history=3, log=logs.append) == 1
    # nonfinite trip
    assert run_gate(hist, [_hist_line(float("inf"))], budget=1.0,
                    drift=4.0, min_history=3, log=logs.append) == 1
    # thin history: drift leg report-only, budget still gates
    thin = hist[:2]
    assert run_gate(thin, [_hist_line(0.01)], budget=1.0, drift=4.0,
                    min_history=3, log=logs.append) == 0
    assert run_gate(thin, [_hist_line(1.5)], budget=1.0, drift=4.0,
                    min_history=3, log=logs.append) == 1
    assert any("THIN" in ln for ln in logs)
    assert any("REGRESSION" in ln for ln in logs)


def test_gate_cli_modes_and_committed_history(tmp_path):
    """CLI exit codes pinned; the committed .accuracy_history.jsonl must
    replay clean (the hermetic CI leg)."""
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    gate = os.path.join(SCRIPTS, "accuracy_gate.py")

    def run(*args):
        return subprocess.run([sys.executable, gate, *args],
                              capture_output=True, env=env,
                              cwd=REPO).returncode

    assert run() == 2                          # no mode selected
    assert run("--replay", "--fresh", "x") == 2   # two modes
    assert run("--replay", "--budget", "0") == 2
    assert run("--replay", "--drift", "0.5") == 2
    assert run("--replay") == 0                # committed history: clean
    missing = str(tmp_path / "none.jsonl")
    assert run("--replay", "--history", missing) == 1


def test_gate_fresh_from_artifact(tmp_path):
    """accuracy records flow from an obs artifact through the shared
    projection into the gate; informational records are skipped."""
    from accuracy_gate import load_fresh, run_gate

    path = _arm(tmp_path)
    accuracy.emit("s", "m", 1e-15, n=64, nb=16, c=60.0, dtype=np.float64)
    accuracy.emit("tridiag_solver", "dc_deflation_fraction", 0.5, n=64,
                  nb=16, c=None, dtype=np.float64)
    accuracy.emit("s", "bad", float("nan"), n=64, nb=16, c=60.0,
                  dtype=np.float64)
    obs.flush()
    fresh = load_fresh([path])
    assert len(fresh) == 2          # budgeted + nonfinite; info skipped
    assert run_gate([], fresh, budget=1.0, drift=4.0, min_history=3,
                    log=lambda *_: None) == 1    # the nonfinite one


def test_gate_inject_drill_trips():
    """The real-fault drill (nan_tile: a poisoned local factor) must
    yield a nonfinite fresh line that regresses the gate."""
    from accuracy_gate import run_gate, run_inject_drill

    fresh = run_inject_drill("nan_tile", log=lambda *_: None)
    assert len(fresh) == 1
    assert math.isinf(fresh[0]["bound_ratio"])
    assert run_gate([], fresh, budget=1.0, drift=4.0, min_history=3,
                    log=lambda *_: None) == 1


def test_gate_inject_corrupt_collective_trips():
    from accuracy_gate import run_gate, run_inject_drill

    fresh = run_inject_drill("corrupt_collective", log=lambda *_: None)
    assert run_gate([], fresh, budget=1.0, drift=4.0, min_history=3,
                    log=lambda *_: None) == 1


# ---------------------------------------------------------------------------
# aggregate table
# ---------------------------------------------------------------------------

def test_aggregate_accuracy_rows():
    from dlaf_tpu.obs.aggregate import accuracy_rows, format_accuracy_table

    recs = [
        {"type": "accuracy", "site": "s", "metric": "m", "rank": 0,
         "value": 1e-15, "bound_ratio": 2e-4},
        {"type": "accuracy", "site": "s", "metric": "m", "rank": 1,
         "value": 2e-15, "bound_ratio": 4e-4},
        {"type": "accuracy", "site": "s", "metric": "bad", "rank": 1,
         "value": None, "nonfinite": True},
        {"type": "span", "name": "x", "dur_s": 0.1},
    ]
    rows = accuracy_rows(recs)
    assert len(rows) == 2
    assert rows[0]["metric"] == "bad" and rows[0]["nonfinite"] == 1
    assert rows[1]["worst_ratio"] == pytest.approx(4e-4)
    assert rows[1]["per_rank"][0]["worst_ratio"] == pytest.approx(2e-4)
    lines = format_accuracy_table(rows)
    assert any("NONFINITE" in ln for ln in lines)
    assert any("s/m" in ln for ln in lines)
