#!/usr/bin/env python
"""Strong-scaling campaign generator.

TPU-native counterpart of the reference's ``scripts/gen_strong.py`` (+
``miniapps.py``/``systems.py``): emits the command lines for a strong-scaling
sweep (fixed problem size, growing device grid) of a chosen miniapp. On a
single-host TPU slice the grid is over local devices; multi-host runs use the
same commands under your launcher.

Usage: python scripts/gen_strong.py --miniapp cholesky -m 32768 -b 512 \
           --grids 1x1 2x2 4x4 8x8 > strong.sh
"""

import argparse

MINIAPPS = {
    "cholesky": "dlaf_tpu.miniapp.miniapp_cholesky",
    "trsm": "dlaf_tpu.miniapp.miniapp_triangular_solver",
    "gen_to_std": "dlaf_tpu.miniapp.miniapp_gen_to_std",
    "reduction_to_band": "dlaf_tpu.miniapp.miniapp_reduction_to_band",
    "eigensolver": "dlaf_tpu.miniapp.miniapp_eigensolver",
}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--miniapp", choices=MINIAPPS, default="cholesky")
    p.add_argument("-m", type=int, default=32768)
    p.add_argument("-b", type=int, default=512)
    p.add_argument("--grids", nargs="+", default=["1x1", "2x2", "4x4", "8x8"])
    p.add_argument("--nruns", type=int, default=5)
    p.add_argument("--nwarmups", type=int, default=1)
    p.add_argument("--type", default="d")
    p.add_argument("--dlaf", nargs="*", default=[],
                   help="extra --dlaf:<knob>=<value> options appended to "
                        "every command (e.g. dist-step-mode=scan)")
    args = p.parse_args()
    extra = "".join(f" --dlaf:{o}" for o in args.dlaf)
    mod = MINIAPPS[args.miniapp]
    print("#!/bin/sh")
    print(f"# strong scaling: {args.miniapp} N={args.m} nb={args.b}")
    for g in args.grids:
        r, c = g.split("x")
        print(f"python -m {mod} -m {args.m} -b {args.b} --grid-rows {r} "
              f"--grid-cols {c} --nruns {args.nruns} --nwarmups {args.nwarmups} "
              f"--type {args.type}{extra}")


if __name__ == "__main__":
    main()
