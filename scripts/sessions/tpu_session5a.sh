#!/usr/bin/env bash
# Round-5 opening session: every verdict item that needs NO new code,
# value-per-minute ordered (the 4h window closed mid-first-arm; assume
# short windows and put the scored-metric + first-ever data up front).
#
# 1. prec probe — the standing emulated-f64 primitive assertion arm
#    (verdict item 10): round/trunc/cast/fma at boundary values.
# 2. config #1 headline re-pin (donated, default knobs) — live TPU
#    number for the driver's bench replay.
# 3. config #1 WITH profile_dir — the perfetto trace that answers the
#    panel/trailing overlap question (verdict item 2). Separate arm:
#    phase fences change the timing methodology.
# 4. z-cholesky 4096 — first complex silicon datum (verdict item 3);
#    exercises the pair-transfer path end-to-end.
# 5. pallas probe — silicon execution or retire (verdict item 6).
# 6. HEGST d/8192 blocked-vs-twosolve A/B (verdict item 7 at 8192).
# 7. z-HEGST 8192 — config #3's type on silicon (verdict item 3).
# 8. eigensolver 8192 with phase table (verdict item 4).
# 9. compile frontier nt=64/128 (verdict item 5) — heavyweight, last.
set -u
cd "$(dirname "$0")/../.."
OUT=${OUT:-$(pwd)/.session5a_$(date +%m%d_%H%M)}
source "$(dirname "$0")/session_lib.sh"

run prec_probe 300 \
    python scripts/tpu_prec_probe.py "$OUT/prec_probe.json"

run chol_4096_donated 1200 \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 4096 -b 256 --nruns 3 --nwarmups 1 --check-result last

run chol_4096_profiled 1200 env DLAF_PROFILE_DIR="$OUT/profile_4096" \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 4096 -b 256 --nruns 2 --nwarmups 1

run zchol_4096 2400 \
    python -m dlaf_tpu.miniapp.miniapp_cholesky --type z \
    -m 4096 -b 256 --nruns 2 --nwarmups 1 --check-result last

run pallas_probe 1500 \
    python scripts/tpu_pallas_probe.py "$OUT/pallas_probe.json"

run hegst_d_8192_blocked 1800 env DLAF_HEGST_IMPL=blocked \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 8192 -b 256 --nruns 2 --nwarmups 1 --check-result last

run hegst_d_8192_twosolve 1800 env DLAF_HEGST_IMPL=twosolve \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 8192 -b 256 --nruns 2 --nwarmups 1 --check-result last

run zhegst_8192 2700 \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std --type z \
    -m 8192 -b 256 --nruns 1 --nwarmups 1 --check-result last

run eig_8192_phases 2700 env DLAF_PROFILE_DIR="$OUT/profile_eig" \
    python -m dlaf_tpu.miniapp.miniapp_eigensolver \
    -m 8192 -b 512 --nruns 1 --check-result last

run compile_frontier 7200 \
    python scripts/tpu_compile_frontier.py "$OUT/compile_frontier.json"

session_summary
