#!/usr/bin/env bash
# Chain the round-5 sessions through one healthy window: 5a (verdict
# items needing no new code) then 5b (the N=16384 holdouts + 4h
# leftovers). Each session's run() helper re-probes health before every
# arm, so a mid-chain wedge skips cleanly instead of hanging.
set -u
cd "$(dirname "$0")/../.."
OUT="$(pwd)/.session5a_live" bash scripts/sessions/tpu_session5a.sh
OUT="$(pwd)/.session5b_live" bash scripts/sessions/tpu_session5b.sh
