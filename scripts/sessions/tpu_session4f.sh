#!/usr/bin/env bash
# Post-peel-fix arms that never got a window, value-per-minute order:
#
# 1. N=16384 config #1 on scan TRAILING + scan ACCUM — the one untested
#    fit combination for the single-chip HBM ceiling (4d: unrolled+xla
#    asked 13.95G, unrolled+scan still runtime-OOM; the scan step form
#    re-uses one step's buffers by construction and scan-accum bounds
#    the live per-shift partials).
# 2. HEGST d/16384 twosolve — the config-#3-family scaling point that
#    confirms (or reverts) the hegst_impl=auto twosolve flip measured
#    at 8192 (364-385 GF/s vs 298 blocked).
# 3. red2band 16384 retry under the now-default scan accumulation —
#    config #4 full-size single-chip attempt (4d runtime-OOMed before
#    ozaki_accum=scan existed).
# 4. N=12288 config #1 post-fix — re-pin the measured single-chip
#    ceiling point (188.9 GF/s pre-fix) at true f64 grade.
set -u
cd "$(dirname "$0")/../.."
OUT=${OUT:-$(pwd)/.session4f_$(date +%m%d_%H%M)}
source "$(dirname "$0")/session_lib.sh"

run chol_16384_scan_scanaccum 2400 env DLAF_CHOLESKY_TRAILING=scan \
    DLAF_OZAKI_ACCUM=scan \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 16384 -b 256 --nruns 1 --nwarmups 1 --check-result last

run hegst_d_16384_twosolve 2400 env DLAF_HEGST_IMPL=twosolve \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 16384 -b 256 --nruns 2 --nwarmups 1 --check-result last

run red2band_16384_scanaccum 2400 env DLAF_DIST_STEP_MODE=scan \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 16384 -b 512 --band-size 128 --nruns 1 --nwarmups 1 \
    --check-result last

run chol_12288_postfix 1800 \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 12288 -b 256 --nruns 2 --nwarmups 1 --check-result last

session_summary
