#!/usr/bin/env bash
# One-shot hardware measurement session (run when the TPU tunnel is healthy).
#
# Runs, in order of value-per-minute, with per-step wall-clock caps so a
# mid-session tunnel wedge still leaves the earlier results on disk:
#   1. bench.py             — the official headline artifact path
#   2. scripts/tpu_sweep.py — ozaki knob grid + panel-latency probes
#   3. single-chip locals of BASELINE configs #2-#4 (round-1 review item 6)
# Results land in $OUT (default /tmp/tpu_session_<ts>/).

set -u
cd "$(dirname "$0")/../.."
OUT=${OUT:-/tmp/tpu_session_$(date +%H%M)}
mkdir -p "$OUT"
echo "results -> $OUT" >&2

run() { # name timeout_s cmd...
  local name=$1 tmo=$2; shift 2
  echo "=== $name ($(date +%T)) ===" >&2
  timeout "$tmo" "$@" >"$OUT/$name.out" 2>"$OUT/$name.log"
  echo "=== $name rc=$? ===" >&2
}

# sweep first: the knob grid + kernel micro numbers are the round's
# decision data; the bench headline (99.8 GF/s ozaki, 2026-07-31 01:05)
# is already recorded in .bench_history.jsonl so bench re-runs last
run sweep 3600 python scripts/tpu_sweep.py

# BASELINE configs #2-#4, single-chip local forms (the multi-chip grids in
# BASELINE.json need hardware this environment does not expose; the local
# runs put first-ever GFLOPS numbers on these code paths — reference
# miniapp_triangular_solver.cpp / miniapp_gen_to_std.cpp /
# miniapp_reduction_to_band.cpp)
run trsm_d_8192 1800 python -m dlaf_tpu.miniapp.miniapp_triangular_solver \
    -m 8192 -n 8192 -b 256 --nruns 3 --nwarmups 1
# same solve with the bulk gemms of the recursive blocked trsm routed
# through the error-free int8 MXU path (f64-grade accuracy — see
# config.f64_gemm; --check-result verifies on hardware)
run trsm_d_8192_mxu 1800 env DLAF_F64_GEMM=mxu \
    python -m dlaf_tpu.miniapp.miniapp_triangular_solver \
    -m 8192 -n 8192 -b 256 --nruns 3 --nwarmups 1 --check-result last
run hegst_z_8192 2400 python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 8192 -b 256 --type z --nruns 3 --nwarmups 1
run red2band_d_16384 2400 python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 16384 -b 512 --band-size 128 --nruns 3 --nwarmups 1

# full local eigensolver pipeline on hardware (phase timers exercise every
# stage: red2band, device band gather, native chase, D&C, back-transforms)
run eig_d_4096 2400 python -m dlaf_tpu.miniapp.miniapp_eigensolver \
    -m 4096 -b 256 --nruns 2 --nwarmups 1 --check-result last

run bench 2700 python bench.py

echo "session done ($(date +%T)); summary:" >&2
grep -h "GFlop/s\|metric" "$OUT"/*.out 2>/dev/null | tail -20 >&2
