# Shared helpers for the one-shot TPU measurement sessions
# (tpu_session4c.sh onward; 4/4b predate this and keep inline copies —
# they were live or already-run when this was extracted, and a running
# bash script must not be edited in place). Source from a session
# script AFTER setting OUT:
#
#   source "$(dirname "$0")/session_lib.sh"
#
# Provides: healthy(), run NAME TIMEOUT CMD..., session_summary.
# Expects: set -u, cwd = repo root, $OUT set.

mkdir -p "$OUT"
export DLAF_COMPILATION_CACHE_DIR="$(pwd)/.jax_cache"
echo "results -> $OUT" >&2

healthy() {
  timeout 90 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
    2>/dev/null
}

run() { # name timeout_s cmd...
  local name=$1 tmo=$2; shift 2
  if ! healthy; then
    echo "=== $name SKIPPED: tunnel re-wedged ($(date +%T)) ===" >&2
    echo "skipped: tunnel re-wedged" >"$OUT/$name.log"
    return 1
  fi
  echo "=== $name ($(date +%T)) ===" >&2
  timeout "$tmo" "$@" >"$OUT/$name.out" 2>"$OUT/$name.log"
  echo "=== $name rc=$? ($(date +%T)) ===" >&2
}

session_summary() {
  echo "session done ($(date +%T)); summary:" >&2
  grep -h "GFlop/s\|check:" "$OUT"/*.out 2>/dev/null | tail -20 >&2
  python scripts/summarize_session.py "$OUT" >"$OUT/summary.json" \
      2>"$OUT/summary.log" || true
}
