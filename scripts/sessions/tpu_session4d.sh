#!/usr/bin/env bash
# Retry session for the next healthy window: the tunnel re-wedged at
# ~19:52 UTC mid-eig_rehearsal (backend init UNAVAILABLE), so session4c's
# arms all skipped and config #5's TPU point is still missing. This
# session re-runs the full 4c ladder (red2band/HEGST under the product
# mxu knobs, the N=16384 OOM diag, the N=12288 ceiling point, the bf16
# retry) and then the config-#5 single-chip eigensolver rehearsal —
# short certain wins first, the long rehearsal last so a mid-window
# wedge costs the least.
set -u
cd "$(dirname "$0")/../.."
OUT=${OUT:-$(pwd)/.session4d_$(date +%m%d_%H%M)}
export OUT
# the 4c ladder shares this OUT; suppress its summary — session_summary
# must run exactly once per directory (it appends duplicates on re-run)
SKIP_SUMMARY=1 bash scripts/sessions/tpu_session4c.sh

source "$(dirname "$0")/session_lib.sh"

# config #5 single-chip rehearsal with the phase table (feeds the TPU
# secular_device_min_k point); knobs now match the product auto defaults
# but stay pinned for label stability
run eig_rehearsal 10800 env DLAF_PROFILE_DIR="$OUT/eig_prof" \
    DLAF_DIST_STEP_MODE=scan DLAF_CHOLESKY_TRAILING=scan \
    DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed \
    python -m dlaf_tpu.miniapp.miniapp_eigensolver \
    -m 8192 -b 512 --nruns 1 --nwarmups 1 --check-result last

session_summary
