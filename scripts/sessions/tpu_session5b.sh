#!/usr/bin/env bash
# Round-5 second session: the two N=16384 single-chip holdouts under the
# round-4-final levers they have never run with (donation landed in 4g,
# free-axis trsm chunking + red2band trail chunking landed AFTER the last
# healthy window), plus the session-4h arms the wedge swallowed.
#
# 1. HEGST d/16384 twosolve — 4g runtime-OOMed pre-chunking; the
#    whole-matrix solves now ride the chunked _solve_local
#    (trsm_rhs_chunk auto = 4096 on TPU at this size).
# 2. HEGST d/16384 blocked — the flop-parity form at the same size
#    (verdict item 7's A/B partner; never attempted at 16384).
# 3. red2band 16384/512/band128 — 4f compile-asked 19.28 GB of 15.75
#    pre-donation pre-chunking; scan + chunked trailing now bounds the
#    mxu workspaces and donation frees one full matrix.
# 4-6. 4h leftovers: red2band 12288 + HEGST d/12288 twosolve (first
#    >8192 family points), TRSM 8192 re-pin under donate_b.
set -u
cd "$(dirname "$0")/../.."
OUT=${OUT:-$(pwd)/.session5b_$(date +%m%d_%H%M)}
source "$(dirname "$0")/session_lib.sh"

run hegst_d_16384_twosolve 3600 env DLAF_HEGST_IMPL=twosolve \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 16384 -b 256 --nruns 1 --nwarmups 1 --check-result last

run hegst_d_16384_blocked 3600 env DLAF_HEGST_IMPL=blocked \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 16384 -b 256 --nruns 1 --nwarmups 1 --check-result last

run red2band_16384 3600 env DLAF_DIST_STEP_MODE=scan \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 16384 -b 512 --band-size 128 --nruns 1 --nwarmups 1 \
    --check-result last

run red2band_12288 2700 env DLAF_DIST_STEP_MODE=scan \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 12288 -b 512 --band-size 128 --nruns 2 --nwarmups 1 \
    --check-result last

run hegst_d_12288_twosolve 2700 env DLAF_HEGST_IMPL=twosolve \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 12288 -b 256 --nruns 2 --nwarmups 1 --check-result last

run trsm_8192_donated 1800 \
    python -m dlaf_tpu.miniapp.miniapp_triangular_solver \
    -m 8192 -b 256 --nruns 3 --nwarmups 1 --check-result last

session_summary
