#!/usr/bin/env bash
# The donation-lever ladder. Every arm runs the working tree's donated
# miniapps (cholesky/gen_to_std/red2band entries consume their per-run
# input copy — the reference's in-place semantics — and internal stage
# hand-offs are always donated). The 4f N=16384 failures all predate
# the lever; each full-matrix buffer returned is 2.1 GB at that size.
#
# 1. N=16384 config #1, default (unrolled ozaki) knobs — 4d asked
#    13.95G of 15.75G; donation frees ~4.2G of that ask.
# 2. N=16384 on scan trailing + scan accum — the bounded-live-set form.
# 3. N=4096 + N=8192 re-pins under donation (program changed: aliasing)
#    — headline continuity for bench.py.
# 4. HEGST d/16384 twosolve donated — 4f runtime-OOMed pre-donation;
#    twosolve now consumes ah/x at each solve and B at the factor.
set -u
cd "$(dirname "$0")/../.."
OUT=${OUT:-$(pwd)/.session4g_$(date +%m%d_%H%M)}
source "$(dirname "$0")/session_lib.sh"

run chol_16384_donated 2700 \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 16384 -b 256 --nruns 1 --nwarmups 1 --check-result last

run chol_16384_scan_donated 2400 env DLAF_CHOLESKY_TRAILING=scan \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 16384 -b 256 --nruns 1 --nwarmups 1 --check-result last

run chol_4096_donated 1200 \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 4096 -b 256 --nruns 3 --nwarmups 1 --check-result last

run chol_8192_donated 1800 \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 8192 -b 256 --nruns 2 --nwarmups 1 --check-result last

run hegst_d_16384_donated 2700 env DLAF_HEGST_IMPL=twosolve \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 16384 -b 256 --nruns 2 --nwarmups 1 --check-result last

session_summary
