#!/usr/bin/env bash
# Round-3 hardware session: retire the built-but-untimed levers (VERDICT r2
# item 2), close configs #3/#4 (item 3), measure the scan premium where it
# matters (item 8 input), and rehearse config #5 on one chip (item 7).
# Ordered by value-per-minute; every step is timeout-guarded and appends
# durable results to .bench_history.jsonl as it lands.
# Results land in $OUT (default /tmp/tpu_session3_<ts>/).

set -u
cd "$(dirname "$0")/../.."
# default under the repo: a container reset must not eat session logs
# (round-2 lesson — the git-tracked history survived, a /tmp log did not)
OUT=${OUT:-$(pwd)/.session3_$(date +%m%d_%H%M)}
mkdir -p "$OUT"
export DLAF_COMPILATION_CACHE_DIR="$(pwd)/.jax_cache"
echo "results -> $OUT" >&2

run() { # name timeout_s cmd...
  local name=$1 tmo=$2; shift 2
  echo "=== $name ($(date +%T)) ===" >&2
  timeout "$tmo" "$@" >"$OUT/$name.out" 2>"$OUT/$name.log"
  echo "=== $name rc=$? ($(date +%T)) ===" >&2
}

# 1. official headline (warm cache; live TPU line replaces the replay)
run bench 2700 python bench.py

# 2. bf16-vs-int8 dot A/B + fixed pallas kernels + panel chain + config #1
# knob grid (the round's designated throughput levers)
run pallas_probe 2400 python scripts/tpu_pallas_probe.py "$OUT/pallas_probe.json"

# 3. N-sweep + scan-vs-unrolled premium in one pass: nt=16/32/64 both
# step formulations, both dot routes at N=8192 (post-_fold_group 16384)
run nsweep_premium 5400 python scripts/tpu_nsweep.py "$OUT/nsweep.json"

# 4. config #3: c128 capability diag, then hegst z/8192 local
run c128_diag 300 python -c "
import jax, numpy as np
jax.config.update('jax_enable_x64', True)
import jax.numpy as jnp
print('devices:', jax.devices())
for dt in (np.complex64, np.complex128):
    try:
        x = jnp.asarray(np.full((8, 8), 1 + 1j, dt))
        y = (x @ x).block_until_ready()
        print(dt.__name__, 'ok ->', y.dtype, np.asarray(y)[0, 0])
    except Exception as e:
        print(dt.__name__, 'FAIL:', repr(e)[:200])
"
# twosolve first: its recursive-trsm program family measured fine on this
# toolchain in round 2 (TRSM d/8192 722 GF/s), so it lands a number even
# if the unrolled 32-step blocked compile proves expensive; blocked second
# for the flop-parity figure
run hegst_z_8192_twosolve 2400 env DLAF_HEGST_IMPL=twosolve \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 8192 -b 256 --type z --nruns 3 --nwarmups 1
run hegst_z_8192_blocked 3600 env DLAF_HEGST_IMPL=blocked \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 8192 -b 256 --type z --nruns 3 --nwarmups 1
# 5. config #4: red2band d/16384/band128 (scan step mode: 127 panels
# would cost ~40 min of unrolled trace on this toolchain)
run red2band_d_16384 2400 env DLAF_DIST_STEP_MODE=scan \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 16384 -b 512 --band-size 128 --nruns 3 --nwarmups 1

# 6. config #2 TRSM: bf16 vs int8 dot route on the mxu path (round-2 best
# 722 GF/s was int8; the s8 HLO dot measured ~1% of MXU peak at micro
# scale, so bf16 may move the full solve too)
run trsm_bf16 1800 env DLAF_F64_GEMM=mxu DLAF_OZAKI_DOT=bf16 \
    python -m dlaf_tpu.miniapp.miniapp_triangular_solver \
    -m 8192 -b 256 --nruns 3 --nwarmups 1
run trsm_int8 1200 env DLAF_F64_GEMM=mxu DLAF_OZAKI_DOT=int8 \
    python -m dlaf_tpu.miniapp.miniapp_triangular_solver \
    -m 8192 -b 256 --nruns 3 --nwarmups 1

# 7. config #5 rehearsal: full eigensolver pipeline on the single chip
# with the phase table on (device reduction vs host chase/D&C vs device
# back-transforms) — first end-to-end hardware wall time
run eig_rehearsal 10800 env DLAF_PROFILE_DIR="$OUT/eig_prof" \
    DLAF_DIST_STEP_MODE=scan DLAF_CHOLESKY_TRAILING=scan \
    DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed \
    python -m dlaf_tpu.miniapp.miniapp_eigensolver \
    -m 8192 -b 512 --nruns 1 --nwarmups 1 --check-result last

echo "session3 done ($(date +%T)); summary:" >&2
grep -h "GFlop/s\|metric\|ok ->\|FAIL\|phases" "$OUT"/*.out "$OUT"/*.log 2>/dev/null | tail -40 >&2
# durable: every TPU miniapp line lands in the git-tracked history
# (bench.py/nsweep/probe already append their own)
python scripts/summarize_session.py "$OUT" >"$OUT/summary.json" 2>&2 || true
