#!/usr/bin/env bash
# Donation re-pins + the newly-fitting scaling points, value-per-minute:
#
# 1. red2band 12288/512/band128 — first-ever config-#4-family point
#    above 8192 on one chip (16384 asked 19.28G; ~(12/16)^2 scaling
#    puts 12288 inside budget with donation).
# 2. HEGST d/12288 twosolve — same logic for the config-#3 family
#    (16384 still OOMs donated; 12288 should fit).
# 3. TRSM config #2 re-pin under donate_b (131 GF/s pre-donation).
# 4. red2band 8192 donated re-pin (142.4 pre-donation).
# 5. eigensolver 8192 rehearsal re-pin (donation now rides the
#    dominant red2band stage; 158.5 s pre-donation).
set -u
cd "$(dirname "$0")/../.."
OUT=${OUT:-$(pwd)/.session4h_$(date +%m%d_%H%M)}
source "$(dirname "$0")/session_lib.sh"

run red2band_12288 2700 env DLAF_DIST_STEP_MODE=scan \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 12288 -b 512 --band-size 128 --nruns 2 --nwarmups 1 \
    --check-result last

run hegst_d_12288_twosolve 2700 env DLAF_HEGST_IMPL=twosolve \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 12288 -b 256 --nruns 2 --nwarmups 1 --check-result last

run trsm_8192_donated 1800 \
    python -m dlaf_tpu.miniapp.miniapp_triangular_solver \
    -m 8192 -b 256 --nruns 3 --nwarmups 1 --check-result last

run red2band_8192_donated 1800 env DLAF_DIST_STEP_MODE=scan \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 8192 -b 512 --band-size 128 --nruns 2 --nwarmups 1 \
    --check-result last

run eig_8192_donated 2700 \
    python -m dlaf_tpu.miniapp.miniapp_eigensolver \
    -m 8192 -b 512 --nruns 1 --check-result last

session_summary
