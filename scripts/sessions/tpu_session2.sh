#!/usr/bin/env bash
# Follow-up hardware session (2026-07-31): what the first session did not
# land before the ~04:30 UTC tunnel wedge, reordered by value-per-minute.
# Results land in $OUT (default /tmp/tpu_session2_<ts>/).

set -u
cd "$(dirname "$0")/../.."
OUT=${OUT:-/tmp/tpu_session2_$(date +%H%M)}
mkdir -p "$OUT"
# persist every step's XLA programs (hegst/red2band compiles cost minutes;
# a killed step's retry must not pay them twice)
export DLAF_COMPILATION_CACHE_DIR="$(pwd)/.jax_cache"
echo "results -> $OUT" >&2

run() { # name timeout_s cmd...
  local name=$1 tmo=$2; shift 2
  echo "=== $name ($(date +%T)) ===" >&2
  timeout "$tmo" "$@" >"$OUT/$name.out" 2>"$OUT/$name.log"
  echo "=== $name rc=$? ===" >&2
}

# 1. official headline (warm cache; auto slices=7 since 2d38671)
run bench 2700 python bench.py

# 2. is complex128 usable on this backend at all? (the hegst_z failure
# at 04:09 was concurrent with the wedge — this separates platform
# capability from tunnel health)
run c128_diag 300 python -c "
import jax, numpy as np
jax.config.update('jax_enable_x64', True)
import jax.numpy as jnp
print('devices:', jax.devices())
for dt in (np.complex64, np.complex128):
    try:
        x = jnp.asarray(np.full((8, 8), 1 + 1j, dt))
        y = (x @ x).block_until_ready()
        print(dt.__name__, 'ok ->', y.dtype, np.asarray(y)[0, 0])
    except Exception as e:
        print(dt.__name__, 'FAIL:', repr(e)[:200])
"

# 3. fixed pallas kernels (predicated square grid, static SMEM loads)
run pallas_probe 2400 python scripts/tpu_pallas_probe.py

# 4. N=16384 cholesky: the scanned step first (compiles O(1); the
# unrolled trace costs ~19 s/step on this toolchain = ~20 min at nt=64),
# then the unrolled ozaki path to validate the incremental-fold OOM fix
run chol_16384 3600 python - <<'EOF'
import os, sys
sys.path.insert(0, "scripts")  # cwd is the repo root (session script cd's)
sys.path.insert(0, ".")
from measure_common import append_history, best_time, log, setup_env
jax = setup_env()
import numpy as np
import dlaf_tpu.config as config
config.initialize()
from dlaf_tpu.algorithms.cholesky import cholesky
from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
from dlaf_tpu.matrix.matrix import Matrix
from dlaf_tpu.miniapp.generators import hpd_element_fn
from dlaf_tpu.types import total_ops

n, nb = 16384, 256
ref = Matrix.from_element_fn(hpd_element_fn(n, np.float64),
                             GlobalElementSize(n, n),
                             TileElementSize(nb, nb), dtype=np.float64)
for variant in ("scan", "ozaki"):
    os.environ["DLAF_CHOLESKY_TRAILING"] = variant
    config.initialize()
    try:
        t = best_time(lambda st: cholesky("L", ref.with_storage(st)).storage,
                      ref.storage + 0)
        g = total_ops(np.float64, n**3 / 6, n**3 / 6) / t / 1e9
        log(f"cholesky N={n} trailing={variant}: {t:.4f}s {g:.1f} GF/s")
        if jax.devices()[0].platform == "tpu":
            append_history("tpu", n, nb, g, t, f"N=16384 trailing={variant}")
    except Exception as e:
        log(f"cholesky N={n} trailing={variant} FAILED: {e!r}"[:400])
    finally:
        os.environ.pop("DLAF_CHOLESKY_TRAILING", None)
EOF

# 5-7. the configs the wedge ate (hegst depends on the c128 diagnosis)
run hegst_z_8192 2400 python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 8192 -b 256 --type z --nruns 3 --nwarmups 1
# 127 panels: the unrolled trace alone would be ~40 min on this
# toolchain — the scan step mode compiles one panel (docs/DESIGN.md)
run red2band_d_16384 2400 env DLAF_DIST_STEP_MODE=scan \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 16384 -b 512 --band-size 128 --nruns 3 --nwarmups 1
run eig_d_4096 2400 python -m dlaf_tpu.miniapp.miniapp_eigensolver \
    -m 4096 -b 256 --nruns 2 --nwarmups 1 --check-result last

echo "session2 done ($(date +%T)); summary:" >&2
grep -h "GFlop/s\|metric\|ok ->\|FAIL" "$OUT"/*.out "$OUT"/*.log 2>/dev/null | tail -30 >&2
