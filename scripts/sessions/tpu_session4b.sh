#!/usr/bin/env bash
# Round-4 follow-up session: the steps the 08:29-09:24 UTC healthy window
# did not reach (the window closed mid-pallas_probe), plus the dot-route
# A/B that window's data made decisive (bf16 full-cholesky measured
# 109.3 GF/s but residual 6.1e-9 vs the 1.7e-9 budget; the int8 arm and
# an on-device bit-compare discriminate MXU-accumulation error from
# route-independent platform error). Armed on scripts/tpu_watch.sh.
set -u
cd "$(dirname "$0")/../.."
OUT=${OUT:-$(pwd)/.session4b_$(date +%m%d_%H%M)}
mkdir -p "$OUT"
export DLAF_COMPILATION_CACHE_DIR="$(pwd)/.jax_cache"
echo "results -> $OUT" >&2

healthy() {
  timeout 90 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
    2>/dev/null
}

run() { # name timeout_s cmd...
  local name=$1 tmo=$2; shift 2
  if ! healthy; then
    echo "=== $name SKIPPED: tunnel re-wedged ($(date +%T)) ===" >&2
    echo "skipped: tunnel re-wedged" >"$OUT/$name.log"
    return 1
  fi
  echo "=== $name ($(date +%T)) ===" >&2
  timeout "$tmo" "$@" >"$OUT/$name.out" 2>"$OUT/$name.log"
  echo "=== $name rc=$? ($(date +%T)) ===" >&2
}

# 1. the decisive dot-route A/B (bit-compare + int8 full-cholesky arm)
run dot_ab 2400 python scripts/tpu_dot_ab.py "$OUT/dot_ab.json"

# 1c. op-level profile of config #1 (perfetto trace parsed offline by
# profile_summary.py — the only instrument that resolves where the 0.2 s
# goes; per-op tunnel probes sit on the ~140 ms RTT floor)
run chol_profile 1200 env DLAF_PROFILE_DIR="$OUT/chol_prof" \
    DLAF_CHOLESKY_TRAILING=ozaki \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 4096 -b 256 --nruns 2 --nwarmups 1
run chol_profile_summary 300 \
    python scripts/profile_summary.py "$OUT/chol_prof" 40

# 2. config #3: c128 capability diag, then hegst z/8192 (first-ever numbers)
run c128_diag 300 python -c "
import jax, numpy as np
jax.config.update('jax_enable_x64', True)
import jax.numpy as jnp
print('devices:', jax.devices())
for dt in (np.complex64, np.complex128):
    try:
        x = jnp.asarray(np.full((8, 8), 1 + 1j, dt))
        y = (x @ x).block_until_ready()
        print(dt.__name__, 'ok ->', y.dtype, np.asarray(y)[0, 0])
    except Exception as e:
        print(dt.__name__, 'FAIL:', repr(e)[:200])
"
run hegst_z_8192_twosolve 2400 env DLAF_HEGST_IMPL=twosolve \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 8192 -b 256 --type z --nruns 3 --nwarmups 1
run hegst_z_8192_blocked 3600 env DLAF_HEGST_IMPL=blocked \
    DLAF_DIST_STEP_MODE=unrolled \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 8192 -b 256 --type z --nruns 3 --nwarmups 1

# 3. config #4: red2band d/16384/band128 (scan step mode; first-ever numbers)
run red2band_d_16384 2400 env DLAF_DIST_STEP_MODE=scan \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 16384 -b 512 --band-size 128 --nruns 3 --nwarmups 1

# 4. N-sweep + scan-vs-unrolled premium ladder (STEP_MODE_AUTO_SCAN_AT)
run nsweep_premium 5400 python scripts/tpu_nsweep.py "$OUT/nsweep.json"

# 5. telescoped red2band scan premium on silicon (local, 31 panels)
run red2band_scan_4096 1800 env DLAF_DIST_STEP_MODE=scan \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 4096 -b 512 --band-size 128 --nruns 2 --nwarmups 1
run red2band_unrolled_4096 2400 env DLAF_DIST_STEP_MODE=unrolled \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 4096 -b 512 --band-size 128 --nruns 2 --nwarmups 1

# 6. config #2 TRSM: bf16 vs int8 dot route on the mxu path
run trsm_bf16 1800 env DLAF_F64_GEMM=mxu DLAF_OZAKI_DOT=bf16 \
    python -m dlaf_tpu.miniapp.miniapp_triangular_solver \
    -m 8192 -b 256 --nruns 3 --nwarmups 1
run trsm_int8 1200 env DLAF_F64_GEMM=mxu DLAF_OZAKI_DOT=int8 \
    python -m dlaf_tpu.miniapp.miniapp_triangular_solver \
    -m 8192 -b 256 --nruns 3 --nwarmups 1

# 7. config #5 rehearsal: full eigensolver on one chip with the phase table
run eig_rehearsal 10800 env DLAF_PROFILE_DIR="$OUT/eig_prof" \
    DLAF_DIST_STEP_MODE=scan DLAF_CHOLESKY_TRAILING=scan \
    DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed \
    python -m dlaf_tpu.miniapp.miniapp_eigensolver \
    -m 8192 -b 512 --nruns 1 --nwarmups 1 --check-result last

echo "session4b done ($(date +%T)); summary:" >&2
grep -h "GFlop/s\|metric\|ok ->\|FAIL\|phases\|mismatch" \
    "$OUT"/*.out "$OUT"/*.log 2>/dev/null | tail -40 >&2
python scripts/summarize_session.py "$OUT" >"$OUT/summary.json" \
    2>"$OUT/summary.log" || true
