#!/usr/bin/env bash
# Round-4 correction pass: session4b's red2band arms ran WITHOUT the
# product's TPU gemm knobs, so their trailing updates took the native
# f64-emulation dot — whose (8, n, n) f32 slice workspaces cost 2x8 GB
# at n=16384 and OOMed the 15.75 GB v5e (config #4, rc=1; allocation
# dump in .session4b_live/red2band_d_16384.log). These arms re-run the
# red2band ladder under DLAF_F64_GEMM=mxu / DLAF_F64_TRSM=mixed — the
# measured-winning TPU route, whose int8 slice planes are 4x smaller —
# sized so at least one config-#4-family number must land. Every arm
# carries --check-result last: a first-ever hardware number without a
# residual is not a number.
set -u
cd "$(dirname "$0")/../.."
OUT=${OUT:-$(pwd)/.session4c_$(date +%m%d_%H%M)}
source "$(dirname "$0")/session_lib.sh"

# 1. smallest first so a number lands before any wedge/OOM surprise:
#    config-#4 family at n=8192 (mxu route; fits with wide margin)
run red2band_8192_scan_mxu 2400 env DLAF_DIST_STEP_MODE=scan \
    DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 8192 -b 512 --band-size 128 --nruns 3 --nwarmups 1 \
    --check-result last

# 2. the full config #4 retry under the mxu route (the OOM decider:
#    int8 slices are 1 B/elt vs the native route's 4 B/elt f32 planes)
run red2band_16384_scan_mxu 3600 env DLAF_DIST_STEP_MODE=scan \
    DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 16384 -b 512 --band-size 128 --nruns 2 --nwarmups 1 \
    --check-result last

# 3. product-route scan-vs-unrolled premium for red2band at 4096
#    (session4b's 4096 arms measured the NATIVE route premium; these
#    measure it on the route the product actually uses on TPU)
run red2band_4096_scan_mxu 1800 env DLAF_DIST_STEP_MODE=scan \
    DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 4096 -b 512 --band-size 128 --nruns 2 --nwarmups 1 \
    --check-result last
run red2band_4096_unrolled_mxu 2400 env DLAF_DIST_STEP_MODE=unrolled \
    DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 4096 -b 512 --band-size 128 --nruns 2 --nwarmups 1 \
    --check-result last

# 4. gen_to_std config-#3 FAMILY on a dtype this tunnel can run: the z
#    (complex128) BASELINE config is environment-gated (complex64 raises
#    UNIMPLEMENTED, c128 transfers hang — .session4b_live/c128_diag),
#    so land the d/8192 arms that exercise the same blocked-HEGST code
#    path (first-ever hardware HEGST numbers either way)
run hegst_d_8192_blocked 2400 env DLAF_HEGST_IMPL=blocked \
    DLAF_DIST_STEP_MODE=unrolled DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 8192 -b 256 --nruns 3 --nwarmups 1 --check-result last
run hegst_d_8192_twosolve 2400 env DLAF_HEGST_IMPL=twosolve \
    DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 8192 -b 256 --nruns 3 --nwarmups 1 --check-result last

# 5. the N=16384 config-#1 OOM (nsweep: RESOURCE_EXHAUSTED on both step
#    forms): capture the allocation dump so the round-5 chunking lever
#    targets the actual top allocations, and bracket the single-chip
#    ceiling with an N=12288 point
run chol_16384_oom_diag 1200 env DLAF_CHOLESKY_TRAILING=ozaki \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 16384 -b 256 --nruns 1 --nwarmups 0
run chol_12288_ozaki 1800 env DLAF_CHOLESKY_TRAILING=ozaki \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 12288 -b 256 --nruns 2 --nwarmups 1 --check-result last

# 6. the one ladder arm lost to a transient remote-compile error
run chol_8192_bf16_retry 1800 env DLAF_CHOLESKY_TRAILING=ozaki \
    DLAF_OZAKI_DOT=bf16 \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 8192 -b 256 --nruns 2 --nwarmups 1 --check-result last

# 7. ozaki_accum=scan A/B: does the O(1)-live-partials scan schedule fit
#    the N=16384 config #1 that OOMs under the default XLA schedule, and
#    what does it cost at a size that fits both ways?
run chol_16384_accum_scan 2400 env DLAF_CHOLESKY_TRAILING=ozaki \
    DLAF_OZAKI_ACCUM=scan \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 16384 -b 256 --nruns 1 --nwarmups 1 --check-result last
run chol_4096_accum_scan 1200 env DLAF_CHOLESKY_TRAILING=ozaki \
    DLAF_OZAKI_ACCUM=scan \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 4096 -b 256 --nruns 2 --nwarmups 1 --check-result last

# SKIP_SUMMARY=1 lets a wrapper session (tpu_session4d.sh) that shares
# this OUT run the one-per-directory summary itself — summarize_session
# appends duplicates on re-run
[ -n "${SKIP_SUMMARY:-}" ] || session_summary
