#!/usr/bin/env bash
# Post-4d opportunistic arms, value-per-minute order, for whatever window
# remains after the 4d ladder + eig rehearsal:
#
# 1. the geqrf probe — decides the round's top code question (is XLA's
#    geqrf the source of red2band's 228x-over-budget TPU residual, or is
#    it larft's triangular_solve?) and A/Bs the new qr_panel=householder
#    route end-to-end at n=2048;
# 2. red2band 4096 under qr_panel=householder — the exact failing 4d
#    config, expected to flip check FAILED -> PASSED if the probe
#    confirms geqrf;
# 3. N=16384 config #1 on the scan TRAILING form + scan accumulation —
#    the one untested fit combination (4d: unrolled+xla 13.95G ask,
#    unrolled+scan still OOM at runtime; the scan step form re-uses one
#    step's buffers by construction);
# 4. HEGST d/16384 twosolve — config-#3-family scaling point on the
#    measured-winning form (385 GF/s at 8192).
set -u
cd "$(dirname "$0")/../.."
OUT=${OUT:-$(pwd)/.session4e_$(date +%m%d_%H%M)}
source "$(dirname "$0")/session_lib.sh"

run geqrf_probe 2400 python scripts/tpu_geqrf_probe.py

run red2band_4096_householder 1800 env DLAF_DIST_STEP_MODE=scan \
    DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed DLAF_QR_PANEL=householder \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 4096 -b 512 --band-size 128 --nruns 2 --nwarmups 1 \
    --check-result last

run chol_16384_scan_scanaccum 2400 env DLAF_CHOLESKY_TRAILING=scan \
    DLAF_OZAKI_ACCUM=scan DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed \
    python -m dlaf_tpu.miniapp.miniapp_cholesky \
    -m 16384 -b 256 --nruns 1 --nwarmups 1 --check-result last

run hegst_d_16384_twosolve 2400 env DLAF_HEGST_IMPL=twosolve \
    DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 16384 -b 256 --nruns 2 --nwarmups 1 --check-result last

session_summary
