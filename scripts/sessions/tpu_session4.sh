#!/usr/bin/env bash
# Round-4 hardware session: convert three rounds of CPU-validated levers into
# silicon numbers (VERDICT r3 items 1-3, 5, 8-input). Ordered by value-per-
# minute under the ~1h-healthy-window assumption: the live headline and the
# first-ever config #3/#4 numbers come before the long sweeps. Every step is
# timeout-guarded and appends durable results to .bench_history.jsonl.
# Results land in $OUT (default <repo>/.session4_<ts>/).

set -u
cd "$(dirname "$0")/../.."
# default under the repo: a container reset must not eat session logs
OUT=${OUT:-$(pwd)/.session4_$(date +%m%d_%H%M)}
mkdir -p "$OUT"
export DLAF_COMPILATION_CACHE_DIR="$(pwd)/.jax_cache"
echo "results -> $OUT" >&2

healthy() { # cheap probe: the tunnel re-wedges mid-session sometimes; a
  # wedged jax.devices() HANGS, so probe in a killable subprocess
  timeout 90 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
    2>/dev/null
}

run() { # name timeout_s cmd...
  local name=$1 tmo=$2; shift 2
  if ! healthy; then
    echo "=== $name SKIPPED: tunnel re-wedged ($(date +%T)) ===" >&2
    echo "skipped: tunnel re-wedged" >"$OUT/$name.log"
    return 1
  fi
  echo "=== $name ($(date +%T)) ===" >&2
  timeout "$tmo" "$@" >"$OUT/$name.out" 2>"$OUT/$name.log"
  echo "=== $name rc=$? ($(date +%T)) ===" >&2
}

# 1. official headline (live TPU line replaces the round-2 replay; since the
# round-4 default flip, bench's ozaki variants ride the bf16 dot route)
run bench 2700 python bench.py

# 2. bf16-vs-int8 dot A/B + fixed pallas kernels + panel chain + config #1
# knob grid (the designated throughput levers; VERDICT r3 weak #1/#2)
run pallas_probe 2400 python scripts/tpu_pallas_probe.py "$OUT/pallas_probe.json"

# 3. config #3: c128 capability diag, then hegst z/8192 (first-ever numbers)
run c128_diag 300 python -c "
import jax, numpy as np
jax.config.update('jax_enable_x64', True)
import jax.numpy as jnp
print('devices:', jax.devices())
for dt in (np.complex64, np.complex128):
    try:
        x = jnp.asarray(np.full((8, 8), 1 + 1j, dt))
        y = (x @ x).block_until_ready()
        print(dt.__name__, 'ok ->', y.dtype, np.asarray(y)[0, 0])
    except Exception as e:
        print(dt.__name__, 'FAIL:', repr(e)[:200])
"
run hegst_z_8192_twosolve 2400 env DLAF_HEGST_IMPL=twosolve \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 8192 -b 256 --type z --nruns 3 --nwarmups 1
# DIST_STEP_MODE=unrolled: nt=32 hits the TPU auto-scan threshold and the
# local reroute (gen_to_std.py) would silently send "blocked" to twosolve —
# this arm exists to pay the unrolled compile for the flop-parity figure
run hegst_z_8192_blocked 3600 env DLAF_HEGST_IMPL=blocked \
    DLAF_DIST_STEP_MODE=unrolled \
    python -m dlaf_tpu.miniapp.miniapp_gen_to_std \
    -m 8192 -b 256 --type z --nruns 3 --nwarmups 1

# 4. config #4: red2band d/16384/band128 (scan step mode; first-ever numbers)
run red2band_d_16384 2400 env DLAF_DIST_STEP_MODE=scan \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 16384 -b 512 --band-size 128 --nruns 3 --nwarmups 1

# 5. N-sweep + scan-vs-unrolled premium ladder (refresh STEP_MODE_AUTO_SCAN_AT
# from hardware data; VERDICT r3 item 5)
run nsweep_premium 5400 python scripts/tpu_nsweep.py "$OUT/nsweep.json"

# 5b. telescoped red2band scan premium on silicon (local, 31 panels —
# the CPU-mesh premium is 1.03x; config #4's single-chip formulation)
run red2band_scan_4096 1800 env DLAF_DIST_STEP_MODE=scan \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 4096 -b 512 --band-size 128 --nruns 2 --nwarmups 1
run red2band_unrolled_4096 2400 env DLAF_DIST_STEP_MODE=unrolled \
    python -m dlaf_tpu.miniapp.miniapp_reduction_to_band \
    -m 4096 -b 512 --band-size 128 --nruns 2 --nwarmups 1

# 6. config #2 TRSM: bf16 vs int8 dot route on the mxu path
run trsm_bf16 1800 env DLAF_F64_GEMM=mxu DLAF_OZAKI_DOT=bf16 \
    python -m dlaf_tpu.miniapp.miniapp_triangular_solver \
    -m 8192 -b 256 --nruns 3 --nwarmups 1
run trsm_int8 1200 env DLAF_F64_GEMM=mxu DLAF_OZAKI_DOT=int8 \
    python -m dlaf_tpu.miniapp.miniapp_triangular_solver \
    -m 8192 -b 256 --nruns 3 --nwarmups 1

# 7. config #5 rehearsal: full eigensolver pipeline on one chip with the
# phase table on (device reduction vs host chase/D&C vs back-transforms)
run eig_rehearsal 10800 env DLAF_PROFILE_DIR="$OUT/eig_prof" \
    DLAF_DIST_STEP_MODE=scan DLAF_CHOLESKY_TRAILING=scan \
    DLAF_F64_GEMM=mxu DLAF_F64_TRSM=mixed \
    python -m dlaf_tpu.miniapp.miniapp_eigensolver \
    -m 8192 -b 512 --nruns 1 --nwarmups 1 --check-result last

echo "session4 done ($(date +%T)); summary:" >&2
grep -h "GFlop/s\|metric\|ok ->\|FAIL\|phases" "$OUT"/*.out "$OUT"/*.log 2>/dev/null | tail -40 >&2
python scripts/summarize_session.py "$OUT" >"$OUT/summary.json" \
    2>"$OUT/summary.log" || true
