"""Knob-bisect the red2band ~1e-5 TPU residual (round 4).

Prior probes (tpu_geqrf_probe.py, tpu_prec_probe.py, 2026-08-02 v5e):
geqrf, larft, triangular_solve, and plain f64 matmul are ALL f64-grade in
isolation on device, and the panel-QR route swap does not move the ~2e-5
end-to-end residual. The remaining differences between the failing TPU
run and the clean CPU control are the ROUTE KNOBS — TPU auto-resolves
f64_gemm=mxu (slices=7, bf16 dots, concat groups, scan accum) and
f64_trsm=mixed where CPU used slices=8/int8/dots/xla — plus the platform
arithmetic itself. This script runs red2band n=2048/nb=512/band=128 on
device under a knob grid, one subprocess per arm (route knobs are
trace-time), and prints one JSON line per arm: the first knob whose flip
restores the ~1e-8 budget is the culprit.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

ARMS = [
    # label, env overrides (on top of the product TPU auto defaults)
    ("auto_defaults", {}),
    ("gemm_native", {"DLAF_F64_GEMM": "native"}),
    ("trsm_native", {"DLAF_F64_TRSM": "native"}),
    ("slices_8", {"DLAF_F64_GEMM_SLICES": "8"}),
    ("dot_int8", {"DLAF_OZAKI_DOT": "int8"}),
    ("group_dots", {"DLAF_OZAKI_GROUP": "dots"}),
    ("accum_xla", {"DLAF_OZAKI_ACCUM": "xla"}),
    ("both_native", {"DLAF_F64_GEMM": "native", "DLAF_F64_TRSM": "native"}),
]

CHILD = r"""
import json, os, sys
import numpy as np
import jax
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, %(repo)r)
from dlaf_tpu import config
from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
from dlaf_tpu.eigensolver.reduction_to_band import reduction_to_band
from dlaf_tpu.matrix.matrix import Matrix
config.initialize()
n, nb, band = 2048, 512, 128
def fn(i, j):
    return np.cos(0.001 * (i * 31 + j * 17)) + np.cos(0.001 * (j * 31 + i * 17))
ref = Matrix.from_element_fn(fn, GlobalElementSize(n, n),
                             TileElementSize(nb, nb), dtype=np.float64)
red = reduction_to_band(ref, band_size=band)
full = red.matrix.to_numpy()
aref = ref.to_numpy()
bd = np.zeros_like(aref)
for rr in range(band + 1):
    d = np.diagonal(full, -rr)
    bd += np.diag(d, -rr)
    if rr:
        bd += np.diag(d.conj(), rr)
w1 = np.linalg.eigvalsh(bd)
w2 = np.linalg.eigvalsh(aref)
resid = np.abs(w1 - w2).max() / np.abs(w2).max()
print(json.dumps({"resid": float(resid),
                  "platform": jax.devices()[0].platform}), flush=True)
"""


def main() -> None:
    os.environ.setdefault("DLAF_COMPILATION_CACHE_DIR",
                          os.path.join(REPO, ".jax_cache"))
    code = CHILD % {"repo": REPO}
    for label, overrides in ARMS:
        env = dict(os.environ)
        env.update(overrides)
        try:
            out = subprocess.run([sys.executable, "-c", code], env=env,
                                 timeout=900, stdout=subprocess.PIPE,
                                 stderr=subprocess.DEVNULL)
            line = out.stdout.decode().strip().splitlines()[-1:]
            r = json.loads(line[0]) if (out.returncode == 0 and line) else \
                {"error": f"rc={out.returncode}"}
        except subprocess.TimeoutExpired:
            r = {"error": "timeout"}
        r["arm"] = label
        r.update(overrides)
        print(json.dumps(r), flush=True)


if __name__ == "__main__":
    main()
