#!/usr/bin/env python
"""Decisive A/B for the ozaki_dot route on real TPU hardware.

The round-4 session's pallas_probe measured the bf16 full-Cholesky arm at
109.3 GF/s with residual 6.1e-9 — 3.5x over the 60*n*eps(2^-47) budget —
but has no int8 arm at the same config, so it cannot tell whether the
excess error is the bf16 dot (MXU f32 accumulation deviating from the
exactness proof in ``ozaki._dot_bf16``) or route-independent platform
error (emulated-f64 panels), the round-2 TRSM pattern.

Three experiments, most decisive first:

1. BIT-COMPARE the slice contraction itself on device: random 7-bit slice
   matrices, int8 route vs bf16 route, k in {1024, 2048, 4096}. Any
   mismatch => the MXU/axon bf16 path is NOT integer-exact and the route
   is mathematically broken at depth, not just imprecise.
2. Full config-#1 Cholesky under dot=int8 with the same residual check as
   the probe's bf16 arm (the missing arm).
3. If (1) finds mismatches: re-compare with half-chunk (2^11) bf16
   accumulation to locate the exactness boundary the hardware honors.

Usage: python scripts/tpu_dot_ab.py [out.json]
Reference protocol: miniapp/miniapp_cholesky.cpp:123-174 (fenced timing).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    from measure_common import cholesky_arm, setup_env

    jax = setup_env()
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    log(f"platform: {platform}, devices: {jax.devices()}")
    results = {"platform": platform, "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
               "bitcompare": {}, "cholesky": {}}

    path = sys.argv[1] if len(sys.argv) > 1 else None

    def emit():
        if path:
            with open(path, "w") as f:
                json.dump(results, f, indent=1, default=float)

    # --- 1. device bit-compare of the two dot routes on raw slices -------
    from dlaf_tpu.tile_ops import ozaki

    rng = np.random.default_rng(7)
    for k in (1024, 2048, 4096):
        ia = rng.integers(-64, 65, (256, k), dtype=np.int8)
        ib = rng.integers(-64, 65, (k, 256), dtype=np.int8)
        ja, jb = jnp.asarray(ia), jnp.asarray(ib)

        i8 = np.asarray(jax.jit(
            lambda a, b: jnp.matmul(a, b, preferred_element_type=jnp.int32)
        )(ja, jb))
        bf = np.asarray(jax.jit(ozaki._dot_bf16)(ja, jb))
        n_mismatch = int((i8 != bf).sum())
        max_abs = int(np.abs(i8.astype(np.int64)
                             - bf.astype(np.int64)).max()) if n_mismatch else 0
        results["bitcompare"][f"k={k}"] = {
            "mismatches": n_mismatch, "total": i8.size, "max_abs_diff": max_abs}
        log(f"bitcompare k={k}: {n_mismatch}/{i8.size} mismatches, "
            f"max |diff| {max_abs}")
        emit()

    # 3. if the full-chunk bf16 dot mismatches, find the boundary the
    # hardware honors: same compare with smaller accumulation chunks
    if any(v["mismatches"] for v in results["bitcompare"].values()):
        def bf16_chunked(a, b, chunk):
            acc = None
            for s0 in range(0, a.shape[-1], chunk):
                p = jnp.matmul(a[..., s0:s0 + chunk].astype(jnp.bfloat16),
                               b[..., s0:s0 + chunk, :].astype(jnp.bfloat16),
                               preferred_element_type=jnp.float32)
                acc = (p.astype(jnp.int32) if acc is None
                       else acc + p.astype(jnp.int32))
            return acc

        k = 4096
        ia = rng.integers(-64, 65, (256, k), dtype=np.int8)
        ib = rng.integers(-64, 65, (k, 256), dtype=np.int8)
        ja, jb = jnp.asarray(ia), jnp.asarray(ib)
        i8 = np.asarray(jax.jit(
            lambda a, b: jnp.matmul(a, b, preferred_element_type=jnp.int32)
        )(ja, jb))
        for chunk in (2048, 1024, 512, 256):
            bf = np.asarray(jax.jit(
                lambda a, b, c=chunk: bf16_chunked(a, b, c))(ja, jb))
            nm = int((i8 != bf).sum())
            results["bitcompare"][f"k={k},chunk={chunk}"] = {
                "mismatches": nm, "total": i8.size}
            log(f"bitcompare k={k} chunk={chunk}: {nm}/{i8.size} mismatches")
            emit()

    # --- 1b. chained trailing-syrk probes: per-op probes are RTT-bound
    # (~140 ms floor), so chain ITERS dependent syrks inside one program
    # and divide — resolves the flop-dominant trailing op's real cost
    # under each (group, dot) combo at the config-#1 step shape
    try:
        from jax import lax

        from dlaf_tpu import config
        from dlaf_tpu.tile_ops import ozaki

        m_, k_, iters = 3840, 256, 12
        rng2 = np.random.default_rng(3)
        a0 = jnp.asarray(rng2.standard_normal((m_, k_)))
        results["chains"] = {}

        def syrk_chain():
            def body(c, _):
                g = ozaki.syrk_f64(c, slices=7)
                # refresh the carry from the output so steps depend on
                # each other without growing magnitude
                nxt = g[:, :k_] / jnp.max(jnp.abs(g))
                return nxt, None

            return jax.jit(lambda a: lax.scan(body, a, None,
                                              length=iters)[0])

        for group in ("dots", "concat"):
            for dot in ("int8", "bf16"):
                os.environ["DLAF_OZAKI_GROUP"] = group
                os.environ["DLAF_OZAKI_DOT"] = dot
                config.initialize()
                try:
                    from measure_common import best_time

                    t = best_time(syrk_chain(), a0)
                    key = f"chain_syrk_{group}_{dot}"
                    results["chains"][key] = {
                        "t_ms_per_step": t / iters * 1e3}
                    log(f"{key}: {t / iters * 1e3:.3f} ms/step "
                        f"(m={m_}, k={k_})")
                finally:
                    os.environ.pop("DLAF_OZAKI_GROUP", None)
                    os.environ.pop("DLAF_OZAKI_DOT", None)
                    config.initialize()
        emit()
    except Exception as e:
        log(f"syrk chain probes FAILED: {e!r}"[:400])

    # --- 2. full config #1: dot routes x group forms, shared protocol ----
    # int8-vs-bf16 decides the residual question (missing arm); the
    # group=concat arms A/B the k-concatenated group sums (one MXU dot
    # per shift group instead of d+1 dots + HBM int32 adds — targets the
    # ~100x gap between the jnp path and the raw dot ceiling)
    for dot, extra in (("int8", None), ("bf16", None),
                       ("bf16", {"DLAF_OZAKI_GROUP": "concat"}),
                       ("int8", {"DLAF_OZAKI_GROUP": "concat"})):
        label = f"impl=jnp,slices=7,dot={dot}" + (
            ",group=concat" if extra else "")
        try:
            results["cholesky"][label] = cholesky_arm(
                "jnp", 7, dot, source="tpu_dot_ab", extra_env=extra)
        except Exception as e:
            log(f"cholesky {label} FAILED: {e!r}"[:600])
        emit()

    log("done")
    emit()


if __name__ == "__main__":
    main()
