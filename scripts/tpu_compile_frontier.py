#!/usr/bin/env python
"""Measure the nt=64 -> nt=128 compile/run frontier on the REAL toolchain.

Round-4 verdict item 5: ``STEP_MODE_AUTO_SCAN_AT`` (config.py) rests on a
chipless-AOT ~19 s/step estimate; no session ever timed the unrolled
compile wall or the scan run premium at north-star step counts (nt=128 is
BASELINE config #1 at nb=128, and the nb=256 form of N=32768). This probe
produces the missing (compile_cost, run_premium) pairs on-tunnel:

  for (nb, nt) in [(256, 64), (128, 128)] at N=16384:
      cold trace+compile wall of the unrolled ozaki local cholesky
      cold trace+compile wall of the scan local cholesky
      one fenced execution of each compiled program (donated input)

Compile timings use a throwaway compilation-cache dir so the "cold" label
is honest even after prior sessions populated ``.jax_cache``. Execution
reuses the just-compiled executables (AOT), so the run premium rides the
same programs the compile walls describe.

The results document is re-printed to stdout after every step so a tunnel
wedge mid-probe keeps everything already measured.

Usage: python scripts/tpu_compile_frontier.py [out.json] [--skip-exec]
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from measure_common import log, setup_env  # noqa: E402

N = int(os.environ.get("DLAF_FRONTIER_N", "16384"))


def main():
    out_path = next((a for a in sys.argv[1:] if not a.startswith("-")), None)
    skip_exec = "--skip-exec" in sys.argv

    # throwaway cache so the "cold" label is honest: set BEFORE setup_env
    # (it setdefaults the same var to the shared .jax_cache)
    cache_dir = tempfile.mkdtemp(prefix="frontier_cache_")
    os.environ["DLAF_COMPILATION_CACHE_DIR"] = cache_dir
    jax = setup_env()
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    log(f"devices: {jax.devices()} (cache: {cache_dir})")
    try:
        _probe(jax, out_path, skip_exec)
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def _probe(jax, out_path, skip_exec):

    import jax.numpy as jnp

    from dlaf_tpu.algorithms.cholesky import (_cholesky_local,
                                              _cholesky_local_scan)
    from dlaf_tpu.common.sync import hard_fence

    results = {"n": N, "platform": jax.devices()[0].platform, "points": []}

    def dump():
        doc = json.dumps(results)
        print(doc, flush=True)
        if out_path:
            with open(out_path, "w") as f:
                f.write(doc + "\n")

    # one O(N^2) analytic HPD host matrix shared by every probe point
    # (donation only consumes the device copy; device_put per point)
    from dlaf_tpu.miniapp.generators import hpd_element_fn

    fn_el = hpd_element_fn(N, np.float64)
    idx = np.arange(N)
    a_host = np.asarray(fn_el(idx[:, None], idx[None, :]), np.float64)

    for nb in (256, 128):
        nt = N // nb
        for mode in ("scan", "unrolled"):
            point = {"nb": nb, "nt": nt, "mode": mode}
            results["points"].append(point)
            try:
                if mode == "unrolled":
                    fn = lambda a, nb=nb: _cholesky_local(
                        a, uplo="L", nb=nb, trailing="ozaki")
                else:
                    fn = lambda a, nb=nb: _cholesky_local_scan(
                        a, uplo="L", nb=nb, use_mxu=True, use_mixed=True)
                jfn = jax.jit(fn, donate_argnums=0)
                spec = jax.ShapeDtypeStruct((N, N), jnp.float64)
                t0 = time.perf_counter()
                lowered = jfn.lower(spec)
                point["trace_s"] = round(time.perf_counter() - t0, 2)
                log(f"[{mode} nb={nb}] traced in {point['trace_s']}s; "
                    "compiling...")
                t0 = time.perf_counter()
                compiled = lowered.compile()
                point["compile_s"] = round(time.perf_counter() - t0, 2)
                log(f"[{mode} nb={nb}] compiled in {point['compile_s']}s")
                dump()
                if skip_exec:
                    continue
                # one fenced execution of the just-compiled program
                a = jax.device_put(a_host)
                hard_fence(a)
                t0 = time.perf_counter()
                r = compiled(a)
                hard_fence(r)
                point["run_s"] = round(time.perf_counter() - t0, 3)
                point["gflops"] = round(N**3 / 3 / point["run_s"] / 1e9, 1)
                log(f"[{mode} nb={nb}] ran in {point['run_s']}s "
                    f"({point['gflops']} GF/s)")
                del a, r, compiled
            except Exception as e:  # keep probing the other points
                point["error"] = f"{type(e).__name__}: {e}"[:400]
                log(f"[{mode} nb={nb}] FAILED: {point['error']}")
            dump()

    dump()


if __name__ == "__main__":
    main()
