#!/usr/bin/env bash
# Tunnel watcher: probe the accelerator every PERIOD seconds; the moment a
# probe succeeds, run the one-shot measurement session (scripts/sessions/tpu_session.sh)
# and exit. The v5e tunnel has shown short healthy windows between long
# wedges (docs/BENCH_LOG_r2.md); this catches the next window unattended.
#
#   OUT=/tmp/tpu_session_X PERIOD=600 MAX_HOURS=10 \
#     SESSION=scripts/sessions/tpu_session2.sh bash scripts/tpu_watch.sh

set -u
cd "$(dirname "$0")/.."
PERIOD=${PERIOD:-600}
MAX_HOURS=${MAX_HOURS:-10}
SESSION=${SESSION:-scripts/sessions/tpu_session.sh}
[ -f "$SESSION" ] || { echo "SESSION $SESSION: no such file" >&2; exit 1; }
deadline=$(( $(date +%s) + MAX_HOURS * 3600 ))

while [ "$(date +%s)" -lt "$deadline" ]; do
  echo "probe $(date -u +%H:%M:%S)" >&2
  if timeout 150 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" 2>/dev/null; then
    echo "tunnel healthy at $(date -u +%H:%M:%S); starting session" >&2
    exec bash "$SESSION"
  fi
  # kill any probe leftovers so wedged inits don't pile up
  sleep "$PERIOD"
done
echo "watcher deadline reached without a healthy probe" >&2
exit 1
