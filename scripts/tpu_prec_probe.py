"""Pin the red2band ~1e-5 TPU residual to a specific op (round 4).

Established so far (scripts/tpu_geqrf_probe.py on the v5e, 2026-08-02):
``geqrf`` is CLEAN on TPU (backward error ~2e-14 at every red2band panel
shape) and the jnp householder panel sweep reproduces the same ~2e-5
end-to-end residual — the defect is in the SHARED path after the panel
factorization. Remaining suspects, probed here in isolation against host
true-f64 oracles:

1. plain (non-ozaki) f64 ``jnp.matmul`` on device — red2band's larft
   Gram (V^H V), ``v @ t``, ``t^H @ m`` ride it; the (check-passing)
   cholesky pipeline routes its big products through ozaki instead. XLA
   TPU matmul precision semantics make this the top suspect.
2. the same matmul under ``jax.default_matmul_precision('highest')`` —
   if 1 is dirty and this is clean, the fix is a precision pin.
3. ``lax.linalg.triangular_solve`` f64 on device — larft's T-solve.
4. ``larft`` end-to-end vs a host-numpy T oracle.

One JSON line per probe. Run standalone on a healthy tunnel, not
concurrently with a session arm (shared HBM).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    platform = jax.devices()[0].platform
    log(f"platform: {platform}")
    rng = np.random.default_rng(11)

    # --- probe 1+2: plain f64 matmul vs precision pin --------------------
    m, k = 1024, 128
    a = rng.standard_normal((m, k))
    ga_host = a.T @ a
    av = jnp.asarray(a, dtype=jnp.float64)
    for label, fn in [
        ("matmul_default", lambda x: x.T @ x),
        ("matmul_highest", lambda x: jnp.matmul(
            x.T, x, precision=lax.Precision.HIGHEST)),
    ]:
        g = np.asarray(jax.jit(fn)(av))
        rel = np.abs(g - ga_host).max() / np.abs(ga_host).max()
        print(json.dumps({"probe": label, "m": m, "k": k,
                          "rel_err": float(rel), "platform": platform}),
              flush=True)

    # small (m,k)@(k,k) like v @ t
    t_small = rng.standard_normal((k, k))
    vt_host = a @ t_small
    got = np.asarray(jax.jit(jnp.matmul)(av, jnp.asarray(t_small)))
    rel = np.abs(got - vt_host).max() / np.abs(vt_host).max()
    print(json.dumps({"probe": "matmul_mk_kk_default", "rel_err": float(rel),
                      "platform": platform}), flush=True)

    # --- probe 3: triangular_solve in isolation ---------------------------
    # well-conditioned upper triangular (unit-ish diagonal)
    u = np.triu(rng.standard_normal((k, k)) * 0.1) + np.eye(k)
    x_host = np.linalg.solve(u, np.eye(k))
    got = np.asarray(jax.jit(lambda m_: lax.linalg.triangular_solve(
        m_, jnp.eye(k, dtype=m_.dtype), left_side=True, lower=False))(
        jnp.asarray(u)))
    rel = np.abs(got - x_host).max() / np.abs(x_host).max()
    print(json.dumps({"probe": "triangular_solve", "k": k,
                      "rel_err": float(rel), "platform": platform}),
          flush=True)

    # --- probe 4: larft vs host oracle ------------------------------------
    from jax._src.lax.linalg import geqrf

    from dlaf_tpu.tile_ops.lapack import larft

    vfull, taus = jax.jit(geqrf)(av)
    v = jnp.tril(vfull, -1) + jnp.eye(m, k, dtype=av.dtype)
    t_dev = np.asarray(jax.jit(larft)(v, taus))
    vn = np.asarray(v)
    tn = np.asarray(taus)
    tinv = np.triu(vn.T @ vn, 1) + np.diag(1.0 / tn)
    t_host = np.linalg.solve(tinv, np.eye(k))
    rel = np.abs(t_dev - t_host).max() / np.abs(t_host).max()
    print(json.dumps({"probe": "larft", "m": m, "k": k,
                      "rel_err": float(rel), "platform": platform}),
          flush=True)


if __name__ == "__main__":
    main()
