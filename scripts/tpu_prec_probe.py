"""Pin the red2band ~1e-5 TPU residual to a specific op (round 4).

Established so far (scripts/tpu_geqrf_probe.py on the v5e, 2026-08-02):
``geqrf`` is CLEAN on TPU (backward error ~2e-14 at every red2band panel
shape) and the jnp householder panel sweep reproduces the same ~2e-5
end-to-end residual — the defect is in the SHARED path after the panel
factorization. Remaining suspects, probed here in isolation against host
true-f64 oracles:

1. plain (non-ozaki) f64 ``jnp.matmul`` on device — red2band's larft
   Gram (V^H V), ``v @ t``, ``t^H @ m`` ride it; the (check-passing)
   cholesky pipeline routes its big products through ozaki instead. XLA
   TPU matmul precision semantics make this the top suspect.
2. the same matmul under ``jax.default_matmul_precision('highest')`` —
   if 1 is dirty and this is clean, the fix is a precision pin.
3. ``lax.linalg.triangular_solve`` f64 on device — larft's T-solve.
4. ``larft`` end-to-end vs a host-numpy T oracle.

One JSON line per probe. Run standalone on a healthy tunnel, not
concurrently with a session arm (shared HBM).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax import lax

    # like the sibling probes: optional argv[1] = durable JSON artifact
    # (one document of all probe lines), re-written after every probe so
    # a mid-probe wedge keeps everything already measured
    out_path = sys.argv[1] if len(sys.argv) > 1 else None
    doc = []

    def emit(obj):
        print(json.dumps(obj), flush=True)
        doc.append(obj)
        if out_path:
            with open(out_path, "w") as f:
                json.dump(doc, f)

    platform = jax.devices()[0].platform
    log(f"platform: {platform}")
    rng = np.random.default_rng(11)

    # --- probe 0: emulated-f64 primitive boundaries -----------------------
    # The peel-corruption bug class (round 4, commit 0807ec7): the TPU
    # 2xf32 emulation's f64 `round` mis-rounds tie+epsilon values
    # (measured on-silicon: round(17.5000005) = 19), which green CPU
    # tests cannot see. Standing assertion arm (VERDICT r4 item 10):
    # compare round/trunc/floor/cast/mul-add on device against the host's
    # true-f64 results at exact ties, tie+-1ulp-ish epsilons, and the
    # int8-saturation rail. A mismatch is a FINDING to record (product
    # code must keep avoiding that primitive), not an infra failure.
    ties = np.array([17.5, 18.5, -17.5, 127.5, -127.5, 0.5, -0.5, 63.5])
    eps = 5e-7     # the measured corruption scale: 17.5000005
    bvals = np.concatenate([ties, ties + eps, ties - eps,
                            np.array([2.0**53 - 1.0, -(2.0**53 - 1.0)])])
    bv = jnp.asarray(bvals, dtype=jnp.float64)
    prim_results = {}
    for label, dev_fn, host_fn in [
        ("round", jax.jit(jnp.round), np.round),
        ("trunc", jax.jit(jnp.trunc), np.trunc),
        ("floor", jax.jit(jnp.floor), np.floor),
        ("cast_f32", jax.jit(lambda x: x.astype(jnp.float32)),
         lambda x: x.astype(np.float32)),
        ("muladd", jax.jit(lambda x: x * 128.0 - jnp.round(x * 128.0)),
         lambda x: x * 128.0 - np.round(x * 128.0)),
    ]:
        got = np.asarray(dev_fn(bv), dtype=np.float64)
        want = np.asarray(host_fn(bvals), dtype=np.float64)
        bad = np.nonzero(got != want)[0]
        prim_results[label] = {"ok": not len(bad),
                               "mismatches": [
                                   {"x": float(bvals[i]), "dev": float(got[i]),
                                    "host": float(want[i])}
                                   for i in bad[:8]]}
        emit(({"probe": f"prim_{label}", "platform": platform,
                          **prim_results[label]}))
    # the exact peel step at the measured corruption value: through the
    # HARDENED path (f32 round + stored-value subtraction) the slices must
    # stay inside the +-65 rail whatever the platform's f64 round does
    from dlaf_tpu.tile_ops import ozaki as oz

    xn = jnp.asarray([17.5000005 / 128.0, 17.4999995 / 128.0, 0.5,
                      -0.4999999], dtype=jnp.float64)
    slices = jax.jit(lambda v: jnp.stack(oz._peel_slices(v, 8)))(xn)
    sl = np.asarray(slices, dtype=np.int64)
    recon = sum(sl[t] * 2.0 ** (-oz.SLICE_BITS * (t + 1)) for t in range(8))
    peel_ok = bool((np.abs(sl) <= 65).all()
                   and np.abs(recon - np.asarray(xn)).max() < 2.0**-53)
    emit(({"probe": "prim_peel_rail", "platform": platform,
                      "ok": peel_ok, "max_abs_slice": int(np.abs(sl).max()),
                      "recon_err": float(np.abs(recon - np.asarray(xn)).max())}))

    # --- probe 1+2: plain f64 matmul vs precision pin --------------------
    # (env-overridable so CI can smoke the probe at tiny shapes on CPU —
    # ci/run.sh full; the on-silicon defaults are the red2band panel shape)
    m = int(os.environ.get("DLAF_PREC_M", "1024"))
    k = int(os.environ.get("DLAF_PREC_K", "128"))
    a = rng.standard_normal((m, k))
    ga_host = a.T @ a
    av = jnp.asarray(a, dtype=jnp.float64)
    for label, fn in [
        ("matmul_default", lambda x: x.T @ x),
        ("matmul_highest", lambda x: jnp.matmul(
            x.T, x, precision=lax.Precision.HIGHEST)),
    ]:
        g = np.asarray(jax.jit(fn)(av))
        rel = np.abs(g - ga_host).max() / np.abs(ga_host).max()
        emit(({"probe": label, "m": m, "k": k,
                          "rel_err": float(rel), "platform": platform}))

    # small (m,k)@(k,k) like v @ t
    t_small = rng.standard_normal((k, k))
    vt_host = a @ t_small
    got = np.asarray(jax.jit(jnp.matmul)(av, jnp.asarray(t_small)))
    rel = np.abs(got - vt_host).max() / np.abs(vt_host).max()
    emit(({"probe": "matmul_mk_kk_default", "rel_err": float(rel),
                      "platform": platform}))

    # --- probe 3: triangular_solve in isolation ---------------------------
    # well-conditioned upper triangular (unit-ish diagonal)
    u = np.triu(rng.standard_normal((k, k)) * 0.1) + np.eye(k)
    x_host = np.linalg.solve(u, np.eye(k))
    got = np.asarray(jax.jit(lambda m_: lax.linalg.triangular_solve(
        m_, jnp.eye(k, dtype=m_.dtype), left_side=True, lower=False))(
        jnp.asarray(u)))
    rel = np.abs(got - x_host).max() / np.abs(x_host).max()
    emit(({"probe": "triangular_solve", "k": k,
                      "rel_err": float(rel), "platform": platform}))

    # --- probe 4: larft vs host oracle ------------------------------------
    from jax._src.lax.linalg import geqrf

    from dlaf_tpu.tile_ops.lapack import larft

    vfull, taus = jax.jit(geqrf)(av)
    v = jnp.tril(vfull, -1) + jnp.eye(m, k, dtype=av.dtype)
    t_dev = np.asarray(jax.jit(larft)(v, taus))
    vn = np.asarray(v)
    tn = np.asarray(taus)
    tinv = np.triu(vn.T @ vn, 1) + np.diag(1.0 / tn)
    t_host = np.linalg.solve(tinv, np.eye(k))
    rel = np.abs(t_dev - t_host).max() / np.abs(t_host).max()
    emit(({"probe": "larft", "m": m, "k": k,
                      "rel_err": float(rel), "platform": platform}))


if __name__ == "__main__":
    main()
