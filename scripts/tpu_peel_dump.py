"""Dump the actual bad entries of the TPU ozaki peel (follow-up to
tpu_ozaki_peel_probe.py: 6/3.7M entries reconstruct 2^-8 off even with
the self-consistent residual subtraction — the truncation hypothesis is
dead; this prints everything about those entries so the real mechanism is
read off, not guessed)."""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SLICE_BITS = 7


def main() -> None:
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from dlaf_tpu import config

    config.initialize()
    from dlaf_tpu.tile_ops import ozaki as oz

    rng = np.random.default_rng(3)
    m, k, s = 1920, 1920, 7
    a = rng.standard_normal((m, k))

    # device normalize+peel, also return the per-step residuals.
    # NOTE: this deliberately re-implements the PRE-FIX peel (emulated-f64
    # jnp.round on the f64 product) rather than calling oz._peel_slices —
    # the probe exists to reproduce the tie+epsilon mis-round mechanism
    # the shipped peel no longer has; on a post-fix tunnel n_bad > 0 here
    # is expected and does NOT indicate product corruption.
    def dev_peel_debug(x):
        sx = oz._scale(x, axis=-1)
        xn = oz._normalize(x, sx)
        out, resids = [], []
        r = xn
        for t in range(s):
            sc = float(2.0 ** (SLICE_BITS * (t + 1)))
            it8 = jnp.round(r * sc).astype(jnp.float32).astype(jnp.int8)
            out.append(it8)
            r = r - it8.astype(jnp.float32).astype(xn.dtype) * (1.0 / sc)
            resids.append(r)
        return xn, sx, out, resids

    xn_d, sx_d, slices_d, resids_d = jax.jit(dev_peel_debug)(jnp.asarray(a))
    xn_d = np.asarray(xn_d)
    slices_d = [np.asarray(x) for x in slices_d]
    resids_d = [np.asarray(x) for x in resids_d]

    recon = sum(slices_d[t].astype(np.float64) * 2.0 ** (-SLICE_BITS * (t + 1))
                for t in range(s))
    err = np.abs(recon - xn_d)
    bad = np.argwhere(err > 1e-6)
    print(json.dumps({"n_bad": int(len(bad))}), flush=True)
    for (i, j) in bad[:10]:
        print(json.dumps({
            "i": int(i), "j": int(j),
            "a": repr(float(a[i, j])),
            "xn_dev": repr(float(xn_d[i, j])),
            "xn_host_from_a": repr(float((a[i, j] / np.abs(a[i]).max()) * 0.5)),
            "err": float(err[i, j]),
            "slices": [int(slices_d[t][i, j]) for t in range(s)],
            "resids_dev": [repr(float(resids_d[t][i, j])) for t in range(s)],
            "rowmax": repr(float(np.abs(a[i]).max())),
            "is_rowmax": bool(np.abs(a[i, j]) == np.abs(a[i]).max()),
        }), flush=True)


if __name__ == "__main__":
    main()
