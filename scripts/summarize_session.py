#!/usr/bin/env python
"""Parse a hardware-session output directory into durable artifacts.

The miniapp drivers print the reference's schema line
(``[i] <t>s <g>GFlop/s <type><uplo> (n, n) (nb, nb) (gr, gc) <threads>
<backend>``) but do not append to ``.bench_history.jsonl`` themselves —
this script closes that gap after a session: it scans ``$OUT/*.out``,
extracts the best timed run per step file, and appends one history line
per step with the step name as the source label. Configs #3/#4's first
hardware numbers land durable this way (VERDICT r2 item 3's Done
criterion). Idempotent-ish: re-running appends duplicates, so run once
per session directory.

Usage: python scripts/summarize_session.py <session_out_dir>
"""

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from measure_common import append_history, log  # noqa: E402

#: matches every miniapp schema variant: an optional extra token after the
#: type field (the eigensolver's "evp"/"gevp" name), and either a (nb, nb)
#: pair or band_to_tridiag's band=N
LINE = re.compile(
    r"\[(\d+)\]\s+([0-9.]+)s\s+([0-9.]+)GFlop/s\s+(\S+)(?:\s+[A-Za-z]\w*)?\s+"
    r"\((\d+),\s*(\d+)\)\s+(?:\((\d+),\s*(\d+)\)|band=(\d+))"
    r".*?\s(\w+)\s*$")

#: step-file prefixes -> dtype letter fallback when the schema letter is
#: compound (e.g. "dL", "zL", "evp")
DTYPES = {"z": "complex128", "c": "complex64", "d": "float64",
          "s": "float32"}


def parse_file(path):
    """Best (highest-GFlop/s) schema line in one step's stdout capture.
    Also picks up the ``[meta] donate=1`` marker (printed by miniapps whose
    timed runs consume their input copies) so the history entry records
    which program — donated or not — was measured; absent marker (older
    session dirs, non-donating miniapps) leaves the flag unrecorded."""
    best = None
    donate = None
    with open(path, errors="replace") as f:
        for line in f:
            if line.strip() == "[meta] donate=1":
                donate = True
                continue
            m = LINE.match(line.strip())
            if not m:
                continue
            t, g = float(m.group(2)), float(m.group(3))
            ty = m.group(4)
            n = int(m.group(5))
            nb = int(m.group(7) or m.group(9) or 0)
            backend = m.group(10)
            dtype = DTYPES.get(ty[0].lower(), "float64")
            if best is None or g > best["gflops"]:
                best = {"t": t, "gflops": g, "n": n, "nb": nb,
                        "dtype": dtype, "backend": backend}
    if best is not None:
        best["donate"] = donate
    return best


def main():
    out_dir = sys.argv[1]
    rows = []
    for name in sorted(os.listdir(out_dir)):
        if not name.endswith(".out"):
            continue
        step = name[:-4]
        best = parse_file(os.path.join(out_dir, name))
        if not best:
            continue
        platform = "tpu" if best["backend"] in ("tpu", "axon") else \
            best["backend"]
        rows.append((step, platform, best))
        if platform == "tpu":
            append_history(platform, best["n"], best["nb"], best["gflops"],
                           best["t"], source=f"session {out_dir} step {step}",
                           variant=step, dtype=best["dtype"],
                           donate=best["donate"])
    for step, platform, best in rows:
        log(f"{step}: {best['gflops']:.1f} GF/s [{platform}] "
            f"n={best['n']} nb={best['nb']} {best['dtype']}")
    print(json.dumps({s: {"gflops": b["gflops"], "platform": p}
                      for s, p, b in rows}))


if __name__ == "__main__":
    main()
