"""Isolate WHERE the mxu (ozaki) gemm route loses ~1e-5 in red2band.

The knob bisect (tpu_red2band_bisect.py, 2026-08-02 v5e) convicted
``f64_gemm=mxu``: native restores 2.5e-14, and the error is
slice-count-INDEPENDENT (s=7 vs 8 changes digit 8) — not the ozaki
mantissa bound, but something structural for these operands on device.

Probes, each vs a host-numpy true-f64 oracle:

1. ``matmul_f64`` / ``syrk_f64`` on random operands at red2band's exact
   shapes ((1920,1920)@(1920,128), (128,1920)@(1920,128), syrk
   (2048,2048)) — is the routed op itself dirty at shape, or only on the
   pipeline's actual data?
2. the first red2band panel step's ACTUAL operands (trail, v, t built on
   device exactly as _red2band_local does), each product stage compared
   mxu-vs-host: W = trail @ (v t);  M = v^T W;  X = W - 1/2 v (t^T M);
   the two-sided update terms X v^T + v X^T.

One JSON line per measurement. Standalone on a healthy tunnel.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def rel(got, want):
    got = np.asarray(got)
    want = np.asarray(want)
    scale = max(np.abs(want).max(), 1e-30)
    return float(np.abs(got - want).max() / scale)


def main() -> None:
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from dlaf_tpu import config

    config.initialize()
    from dlaf_tpu.tile_ops.ozaki import matmul_f64, syrk_f64

    platform = jax.devices()[0].platform
    log(f"platform: {platform}")
    rng = np.random.default_rng(3)

    # --- probe 1: routed ops on random operands at shape -----------------
    m, k = 1920, 128
    big = rng.standard_normal((m, m))
    thin = rng.standard_normal((m, k))
    for label, fn, args, want in [
        ("matmul_big_thin", matmul_f64, (big, thin), big @ thin),
        ("matmul_thin_T_big", matmul_f64, (thin.T, big), thin.T @ big),
        ("syrk_2048", syrk_f64, (big,), big @ big.T),
    ]:
        got = jax.jit(fn)(*(jnp.asarray(x) for x in args))
        print(json.dumps({"probe": label, "rel_err": rel(got, want),
                          "platform": platform}), flush=True)

    # --- probe 2: the first panel step's actual operands -----------------
    from jax._src.lax.linalg import geqrf

    from dlaf_tpu.tile_ops.lapack import larft

    n, nb = 2048, 128

    def fn(i, j):
        return np.cos(0.001 * (i * 31 + j * 17)) + np.cos(0.001 * (j * 31 + i * 17))

    i, j = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    a_host = fn(i, j)
    av = jnp.asarray(a_host, dtype=jnp.float64)

    def first_panel(av):
        panel = av[nb:, 0:nb]
        vfull, taus = geqrf(panel)
        v = jnp.tril(vfull, -1) + jnp.eye(n - nb, nb, dtype=av.dtype)
        t = larft(v, taus)
        trail = av[nb:, nb:]
        return v, t, trail

    v, t, trail = jax.jit(first_panel)(av)
    vh, th, trailh = (np.asarray(x) for x in (v, t, trail))

    vt_h = vh @ th
    w_h = trailh @ vt_h
    m_h = vh.T @ w_h
    x_h = w_h - 0.5 * vh @ (th.T @ m_h)
    upd_h = trailh - x_h @ vh.T - vh @ x_h.T

    vt = jax.jit(jnp.matmul)(v, t)
    w = jax.jit(matmul_f64)(trail, vt)
    print(json.dumps({"probe": "step_W", "rel_err": rel(w, w_h),
                      "vt_rel": rel(vt, vt_h),
                      "platform": platform}), flush=True)
    mm = jax.jit(matmul_f64)(jnp.swapaxes(v, -1, -2), jnp.asarray(w_h))
    print(json.dumps({"probe": "step_M", "rel_err": rel(mm, m_h),
                      "platform": platform}), flush=True)

    def xupd(v, t, trail, w, m_):
        x = w - 0.5 * v @ (t.T @ m_)
        return trail - matmul_f64(x, jnp.swapaxes(v, -1, -2)) \
            - matmul_f64(v, jnp.swapaxes(x, -1, -2))

    upd = jax.jit(xupd)(v, t, trail, jnp.asarray(w_h), jnp.asarray(m_h))
    print(json.dumps({"probe": "step_update", "rel_err": rel(upd, upd_h),
                      "platform": platform}), flush=True)
    # the annihilation quality: rows that should be eliminated (band
    # boundary at nb) — absolute mass below the band in the updated block
    below = np.tril(np.asarray(upd), -1)[nb:, :]  # noqa - context only
    print(json.dumps({"probe": "step_done", "platform": platform}),
          flush=True)


if __name__ == "__main__":
    main()
