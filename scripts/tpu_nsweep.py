#!/usr/bin/env python
"""Cholesky N-sweep + scan-vs-unrolled premium on one chip.

Covers two round-3 items in one pass over N in {4096, 8192, 16384}
(nt = 16/32/64 at nb=256):

* the unrolled ozaki path's panel-latency amortization curve, including
  the first post-``_fold_group`` attempt at N=16384 (the collect-then-
  combine form OOM'd HBM at compile: 22.68 GB vs 15.75) and the
  bf16-vs-int8 slice-dot A/B at N=8192 where trailing flops dominate;
* the scan formulation's run premium on real hardware (the 2.1x figure in
  docs/DESIGN.md is a CPU-mesh number at nt=16) — the input the
  ``dist_step_mode`` auto default needs (VERDICT r2 item 8).

Each combo is guarded; results append to ``.bench_history.jsonl`` as they
land and the results document re-prints after every combo.

Usage: python scripts/tpu_nsweep.py [out.json]
"""

import gc
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from measure_common import append_history, best_time, log, setup_env  # noqa: E402

#: (N, variant, knobs) in value-per-minute order: the known-good unrolled
#: N=8192 first (re-confirm 286 GF/s), then its bf16 A/B (the round's
#: designated lever), then the scan premium ladder, then the post-OOM-fix
#: N=16384 runs (scan before unrolled: O(1) compile vs ~19 s/step).
COMBOS = [
    (8192, "ozaki", {"DLAF_OZAKI_DOT": "int8"}),
    (8192, "ozaki", {"DLAF_OZAKI_DOT": "bf16"}),
    (4096, "scan", {"DLAF_F64_GEMM": "mxu", "DLAF_F64_TRSM": "mixed"}),
    (4096, "ozaki", {}),  # same-session tie point for the premium table
    (8192, "scan", {"DLAF_F64_GEMM": "mxu", "DLAF_F64_TRSM": "mixed"}),
    (8192, "scan", {"DLAF_F64_GEMM": "mxu", "DLAF_F64_TRSM": "mixed",
                    "DLAF_OZAKI_DOT": "bf16"}),
    # 16384 last: every smaller input is evicted by then, so the whole
    # HBM budget minus the 2 GB input is available for the post-
    # _fold_group compile
    (16384, "scan", {"DLAF_F64_GEMM": "mxu", "DLAF_F64_TRSM": "mixed"}),
    (16384, "ozaki", {}),
]

KNOB_KEYS = ("DLAF_CHOLESKY_TRAILING", "DLAF_OZAKI_DOT", "DLAF_F64_GEMM",
             "DLAF_F64_TRSM", "DLAF_OZAKI_IMPL", "DLAF_F64_GEMM_SLICES")

#: DLAF_NSWEEP_SMOKE=1 shrinks every N by 16x (and nb to 64) so the
#: script's control flow is testable off-hardware in seconds; history
#: appends stay disabled off-TPU either way.
SMOKE = bool(os.environ.get("DLAF_NSWEEP_SMOKE"))


def main():
    jax = setup_env()
    import dlaf_tpu.config as config
    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix
    from dlaf_tpu.miniapp.generators import hpd_element_fn
    from dlaf_tpu.types import total_ops

    platform = jax.devices()[0].platform
    log(f"platform: {platform}, devices: {jax.devices()}")
    results = {"platform": platform, "nb": 256, "runs": {}}

    def emit():
        print(json.dumps(results, default=float), flush=True)

    nb = 64 if SMOKE else 256
    combos = [(n // 16 if SMOKE else n, v, kn) for n, v, kn in COMBOS]
    results["nb"] = nb
    # one generator pass per N, shared across combos — and EVICTED after a
    # size's last combo: a dead N=8192 input pins 512 MB of the 15.75 GB
    # HBM budget exactly when the N=16384 runs need the headroom
    last_combo_idx = {n: i for i, (n, _, _) in enumerate(combos)}
    mats = {}
    for ci, (n, variant, knobs) in enumerate(combos):
        key = f"N={n} {variant} " + ",".join(
            f"{k.lower().replace('dlaf_', '')}={v}" for k, v in knobs.items())
        for k in KNOB_KEYS:
            os.environ.pop(k, None)
        os.environ["DLAF_CHOLESKY_TRAILING"] = variant
        os.environ.update(knobs)
        config.initialize()
        try:
            if n not in mats:
                mats[n] = Matrix.from_element_fn(
                    hpd_element_fn(n, np.float64), GlobalElementSize(n, n),
                    TileElementSize(nb, nb), dtype=np.float64)
            ref = mats[n]
            t = best_time(
                lambda st: cholesky("L", ref.with_storage(st)).storage,
                ref.storage + 0, reps=3)
            g = total_ops(np.float64, n**3 / 6, n**3 / 6) / t / 1e9
            results["runs"][key] = {"t": t, "gflops": g}
            log(f"{key}: {t:.4f}s {g:.1f} GF/s")
            if platform == "tpu":
                append_history("tpu", n, nb, g, t,
                               f"tpu_nsweep {key}", variant=variant)
        except Exception as e:
            results["runs"][key] = {"error": repr(e)[:300]}
            log(f"{key} FAILED: {e!r}"[:500])
        finally:
            for k in KNOB_KEYS:
                os.environ.pop(k, None)
            config.initialize()
            if last_combo_idx[n] == ci:
                mats.pop(n, None)
            gc.collect()
        emit()

    # premium table: scan_t / unrolled_t per nt where both landed
    prem = {}
    for n in sorted({n for n, _, _ in combos}):
        uk = [k for k in results["runs"]
              if k.startswith(f"N={n} ozaki") and "t" in results["runs"][k]]
        sk = [k for k in results["runs"]
              if k.startswith(f"N={n} scan") and "t" in results["runs"][k]]
        if uk and sk:
            tu = min(results["runs"][k]["t"] for k in uk)
            ts = min(results["runs"][k]["t"] for k in sk)
            prem[f"nt={n // nb}"] = {"unrolled_t": tu, "scan_t": ts,
                                     "premium": ts / tu}
    results["scan_premium"] = prem
    log(f"scan premium: {prem}")
    emit()

    path = sys.argv[1] if len(sys.argv) > 1 else None
    if path:
        with open(path, "w") as f:
            json.dump(results, f, default=float)
        log(f"wrote {path}")


if __name__ == "__main__":
    main()
