"""Shared measurement protocol for the hardware scripts.

The fenced ``best_time`` here is the measurement contract the bench
artifacts cite (BASELINE.md): 1 warmup (compile) + ``REPS`` timed
iterations, each bounded by :func:`dlaf_tpu.common.sync.hard_fence`
(``block_until_ready`` alone is not a reliable barrier through
tunnel-proxied PJRT backends). Scripts must share this module rather
than copying it so the protocol cannot drift between artifacts.
"""

from __future__ import annotations

import os
import sys
import time

REPS = int(os.environ.get("DLAF_SWEEP_REPS", "4"))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup_env():
    """x64 + persistent compile cache; returns the jax module.

    Honors a ``JAX_PLATFORMS`` env request at the config level too: the
    accelerator plugin's register() force-sets ``jax_platforms`` at
    interpreter start, overriding the env var — without this, a script run
    with ``JAX_PLATFORMS=cpu`` still probes the (possibly wedged) tunnel
    and hangs (same workaround as tests/conftest.py)."""
    import jax

    requested = os.environ.get("JAX_PLATFORMS")
    if requested:
        jax.config.update("jax_platforms", requested)
    jax.config.update("jax_enable_x64", True)
    os.environ.setdefault("DLAF_COMPILATION_CACHE_DIR",
                          os.path.join(repo_root(), ".jax_cache"))
    return jax


def best_time(fn, *args, reps: int = None, return_last: bool = False):
    """min over ``reps`` fenced timings after one warmup call.
    ``return_last=True`` returns ``(t, out)`` with the last run's output,
    so callers that also validate the result don't pay an extra run."""
    from dlaf_tpu.common.sync import hard_fence

    out = fn(*args)
    hard_fence(*(out if isinstance(out, tuple) else (out,)))
    times = []
    for _ in range(REPS if reps is None else reps):
        t0 = time.perf_counter()
        out = fn(*args)
        hard_fence(*(out if isinstance(out, tuple) else (out,)))
        times.append(time.perf_counter() - t0)
    return (min(times), out) if return_last else min(times)


def append_history(platform: str, n: int, nb: int, gflops: float, t: float,
                   source: str, variant: str = "ozaki",
                   dtype: str = "float64", donate: bool = None,
                   workload: str = None, extra: dict = None):
    """Append one measurement to the git-tracked append-only history log
    and return the line dict (line schema owned by ``dlaf_tpu.obs.sinks``
    — bench.py prints the returned dict rather than rebuilding it): a
    later tunnel wedge or container reset must never cost an
    already-landed hardware number — bench.py's CPU-fallback path
    surfaces the best recorded TPU entry from this file.

    The line is schema-validated BEFORE it is written
    (``obs.append_history_line``): a non-finite measurement raises
    ValueError here, loudly, instead of landing in the log and silently
    skewing every later replayed-history headline and bench-gate
    baseline. Disk errors stay non-fatal (the measurement survives on
    stdout/artifact)."""
    import time as _time

    line = {"variant": variant, "platform": platform, "dtype": dtype,
            "n": n, "nb": nb, "gflops": round(float(gflops), 2),
            "t": float(t),
            # UTC: bench.py's PEEL_FIX_TS pre/post-fix cutoff is UTC-anchored
            "ts": _time.strftime("%Y-%m-%dT%H:%M:%S", _time.gmtime()),
            "source": source}
    if donate is not None:
        # the donated program aliases its input (different measured program
        # from the pre-donation entries in this log — round-4 advisory):
        # record the flag so cross-round comparisons can tell them apart
        line["donate"] = bool(donate)
    if workload is not None:
        # non-cholesky workloads (bench.py's eigensolver stage arms carry
        # different flop models): labeled so the cholesky headline and
        # its replayed-history lookup never pick them up
        line["workload"] = str(workload)
    if extra:
        # workload-specific side fields (e.g. the serve arm's
        # batched-vs-singles speedup that scripts/bench_gate.py holds to
        # the ISSUE-11 floor); never part of the required line schema,
        # and never allowed to shadow it
        line = {**{k: v for k, v in extra.items() if k not in line}, **line}
    from dlaf_tpu.obs import append_history_line

    # DLAF_BENCH_HISTORY_PATH redirects the durable log (CI runs the
    # serve bench arm for the speedup gate and must not mutate the
    # git-tracked baseline file with container-local numbers — the gate
    # reads the obs artifact's bench_result records, not the history)
    path = os.environ.get("DLAF_BENCH_HISTORY_PATH") or os.path.join(
        repo_root(), ".bench_history.jsonl")
    try:
        append_history_line(path, line)
    except OSError as e:
        log(f"history append failed: {e!r}")
    return line


def append_accuracy_history(platform: str, site: str, metric: str, n: int,
                            nb: int, value: float, bound_ratio: float,
                            source: str, dtype: str = "float64"):
    """Append one accuracy measurement to the git-tracked append-only
    accuracy history (``.accuracy_history.jsonl`` — the drift baseline of
    ``scripts/accuracy_gate.py``). Line schema owned by
    ``dlaf_tpu.obs.sinks`` (kind="accuracy", the same validating reader
    the gates share); a non-finite value raises here, loudly, instead of
    poisoning every later drift baseline. Disk errors stay non-fatal."""
    import time as _time

    line = {"site": site, "metric": metric, "platform": platform,
            "dtype": dtype, "n": n, "nb": nb, "value": float(value),
            "bound_ratio": float(bound_ratio),
            "ts": _time.strftime("%Y-%m-%dT%H:%M:%S", _time.gmtime()),
            "source": source}
    from dlaf_tpu.obs import append_history_line

    try:
        append_history_line(os.path.join(repo_root(),
                                         ".accuracy_history.jsonl"), line,
                            kind="accuracy")
    except OSError as e:
        log(f"accuracy history append failed: {e!r}")
    return line


def peel(x, s: int):
    """Stacked int8 Ozaki slices + the row scale (micro-kernel input)."""
    import jax.numpy as jnp

    from dlaf_tpu.tile_ops import ozaki as oz

    sa = oz._scale(x, axis=-1)
    return jnp.stack(oz._peel_slices(oz._normalize(x, sa), s)), sa


def cholesky_arm(impl: str, slices: int, dot: str, *, n: int = 4096,
                 nb: int = 256, source: str, extra_env: dict = None):
    """One config-#1 Cholesky measurement under the given ozaki knobs,
    with the miniapp-grade residual check — THE shared protocol for every
    script's full-cholesky arm (probe-identical by construction, per this
    module's no-copy contract). Returns ``{t, gflops, residual, tol,
    check}``; on a passing TPU run the result is appended to the durable
    history as ``"<source> impl=...,slices=...,dot=..."``. Knobs are
    restored and config re-initialized on exit."""
    import jax
    import numpy as np

    from dlaf_tpu import config
    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix
    from dlaf_tpu.miniapp.checks import effective_eps
    from dlaf_tpu.miniapp.generators import hpd_element_fn
    from dlaf_tpu.types import total_ops

    extra_env = dict(extra_env or {})
    key = f"impl={impl},slices={slices},dot={dot}" + "".join(
        f",{k.removeprefix('DLAF_').lower()}={v}"
        for k, v in sorted(extra_env.items()))
    for k, v in extra_env.items():
        os.environ[k] = v
    os.environ["DLAF_CHOLESKY_TRAILING"] = "ozaki"
    os.environ["DLAF_OZAKI_IMPL"] = impl
    os.environ["DLAF_F64_GEMM_SLICES"] = str(slices)
    os.environ["DLAF_OZAKI_DOT"] = dot
    config.initialize()
    try:
        ref = Matrix.from_element_fn(
            hpd_element_fn(n, np.float64), GlobalElementSize(n, n),
            TileElementSize(nb, nb), dtype=np.float64)

        def run(st):
            return cholesky("L", ref.with_storage(st)).storage

        t, last = best_time(run, ref.storage + 0, return_last=True)
        g = total_ops(np.float64, n**3 / 6, n**3 / 6) / t / 1e9
        lfac = np.tril(np.asarray(ref.with_storage(last).to_numpy()))
        aref = np.asarray(ref.to_numpy())
        ah = np.tril(aref) + np.tril(aref, -1).T
        resid = float(np.linalg.norm(lfac @ lfac.T - ah)
                      / np.linalg.norm(ah))
        # judge tolerance from the devices that produced the result
        # (`of=last`), not the process default backend
        eps, _ = effective_eps(np.float64, of=last)
        tol = 60 * n * eps
        out = {"t": float(t), "gflops": float(g), "residual": resid,
               "tol": float(tol), "check": bool(resid < tol)}
        log(f"cholesky N={n} {key}: {t:.4f}s {g:.1f} GF/s "
            f"residual={resid:.3e} tol={tol:.3e} "
            f"({'PASS' if out['check'] else 'FAIL'})")
        if jax.devices()[0].platform == "tpu" and out["check"]:
            append_history("tpu", n, nb, g, t, f"{source} {key}")
            # paired accuracy entry: every durable perf point carries its
            # residual grade, so accuracy_gate's drift baseline grows
            # alongside the bench one (docs/accuracy.md)
            append_accuracy_history("tpu", "cholesky_arm",
                                    "cholesky_residual", n, nb, resid,
                                    resid / tol, f"{source} {key}")
        return out
    finally:
        for k_ in ("DLAF_CHOLESKY_TRAILING", "DLAF_OZAKI_IMPL",
                   "DLAF_F64_GEMM_SLICES", "DLAF_OZAKI_DOT",
                   *extra_env):
            os.environ.pop(k_, None)
        config.initialize()
