"""Shared measurement protocol for the hardware scripts.

The fenced ``best_time`` here is the measurement contract the bench
artifacts cite (BASELINE.md): 1 warmup (compile) + ``REPS`` timed
iterations, each bounded by :func:`dlaf_tpu.common.sync.hard_fence`
(``block_until_ready`` alone is not a reliable barrier through
tunnel-proxied PJRT backends). Scripts must share this module rather
than copying it so the protocol cannot drift between artifacts.
"""

from __future__ import annotations

import os
import sys
import time

REPS = int(os.environ.get("DLAF_SWEEP_REPS", "4"))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup_env():
    """x64 + persistent compile cache; returns the jax module.

    Honors a ``JAX_PLATFORMS`` env request at the config level too: the
    accelerator plugin's register() force-sets ``jax_platforms`` at
    interpreter start, overriding the env var — without this, a script run
    with ``JAX_PLATFORMS=cpu`` still probes the (possibly wedged) tunnel
    and hangs (same workaround as tests/conftest.py)."""
    import jax

    requested = os.environ.get("JAX_PLATFORMS")
    if requested:
        jax.config.update("jax_platforms", requested)
    jax.config.update("jax_enable_x64", True)
    os.environ.setdefault("DLAF_COMPILATION_CACHE_DIR",
                          os.path.join(repo_root(), ".jax_cache"))
    return jax


def best_time(fn, *args, reps: int = None, return_last: bool = False):
    """min over ``reps`` fenced timings after one warmup call.
    ``return_last=True`` returns ``(t, out)`` with the last run's output,
    so callers that also validate the result don't pay an extra run."""
    from dlaf_tpu.common.sync import hard_fence

    out = fn(*args)
    hard_fence(*(out if isinstance(out, tuple) else (out,)))
    times = []
    for _ in range(REPS if reps is None else reps):
        t0 = time.perf_counter()
        out = fn(*args)
        hard_fence(*(out if isinstance(out, tuple) else (out,)))
        times.append(time.perf_counter() - t0)
    return (min(times), out) if return_last else min(times)


def append_history(platform: str, n: int, nb: int, gflops: float, t: float,
                   source: str, variant: str = "ozaki",
                   dtype: str = "float64"):
    """Append one measurement to the git-tracked append-only history log
    (same schema as bench.py's run_variant): a later tunnel wedge or
    container reset must never cost an already-landed hardware number —
    bench.py's CPU-fallback path surfaces the best recorded TPU entry
    from this file."""
    import json
    import time as _time

    line = {"variant": variant, "platform": platform, "dtype": dtype,
            "n": n, "nb": nb, "gflops": round(float(gflops), 2),
            "t": float(t),
            "ts": _time.strftime("%Y-%m-%dT%H:%M:%S"), "source": source}
    try:
        with open(os.path.join(repo_root(), ".bench_history.jsonl"),
                  "a") as f:
            f.write(json.dumps(line) + "\n")
    except OSError as e:
        log(f"history append failed: {e!r}")


def peel(x, s: int):
    """Stacked int8 Ozaki slices + the row scale (micro-kernel input)."""
    import jax.numpy as jnp

    from dlaf_tpu.tile_ops import ozaki as oz

    sa = oz._scale(x, axis=-1)
    return jnp.stack(oz._peel_slices(oz._normalize(x, sa), s)), sa
