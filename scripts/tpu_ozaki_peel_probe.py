"""Bisect INSIDE the ozaki f64 gemm on TPU: peel vs dot vs recombine.

tpu_ozaki_shape_probe.py (2026-08-02 v5e) showed matmul_f64 itself dirty
at deep contractions on device (3.9e-4 at (1920,1920)@(1920,128), 4.4e-5
on syrk-2048) while k=128 products are clean — slice-count-independent,
data-dependent. This splits the route into its three stages:

1. REPRESENTATION: peel slices on device, reconstruct
   ``sum_t I_t 2^-q(t+1)`` on the host in true f64, compare against the
   device-normalized operand — is the peel/round/residual loop (all
   emulated-f64 elementwise ops) producing a faithful decomposition?
2. DOT+RECOMBINE on KNOWN-GOOD slices: peel on the HOST in true f64,
   push the int8 slices to device, run the group dots + f64 fold there,
   compare against the host int-exact oracle — are the MXU dots / int32
   sums / emulated-f64 fold clean when fed exact slices?
3. cross: device peel + host-exact dot of those slices — closes the
   matrix: whichever stage carries the ~1e-4 is convicted.

One JSON line per measurement. Standalone on a healthy tunnel.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SLICE_BITS = 7


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def host_peel(xn, s):
    """True-f64 host peeling (the reference decomposition)."""
    out = []
    r = xn.copy()
    for t in range(s):
        sc = float(2.0 ** (SLICE_BITS * (t + 1)))
        it = np.round(r * sc)
        out.append(it.astype(np.int8))
        r = r - it * (1.0 / sc)
    return out


def host_recombine(ia, ib, s):
    """Int-exact host oracle of the group dots + fold (f64 throughout)."""
    acc = np.zeros((ia[0].shape[0], ib[0].shape[1]))
    for d in range(s):
        p = np.zeros_like(acc)
        for t in range(d + 1):
            p += ia[t].astype(np.int64).T.astype(np.float64).T @ \
                ib[d - t].astype(np.float64)
        acc += p * float(2.0 ** (-SLICE_BITS * (d + 2)))
    return acc


def main() -> None:
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from dlaf_tpu import config

    config.initialize()
    from dlaf_tpu.tile_ops import ozaki as oz

    platform = jax.devices()[0].platform
    log(f"platform: {platform}")
    rng = np.random.default_rng(3)
    m, k, ncols, s = 1920, 1920, 128, 7

    a = rng.standard_normal((m, k))
    b = rng.standard_normal((k, ncols))

    # device-side normalize + peel (jitted exactly like the product path)
    def dev_peel(x, axis):
        sx = oz._scale(x, axis=axis)
        xn = oz._normalize(x, sx)
        return oz._peel_slices(xn, s), sx, xn

    (ia_d, sa_d, an_d) = jax.jit(lambda x: dev_peel(x, -1))(jnp.asarray(a))
    (ib_d, sb_d, bn_d) = jax.jit(lambda x: dev_peel(x, -2))(jnp.asarray(b))
    ia_d = [np.asarray(x) for x in ia_d]
    ib_d = [np.asarray(x) for x in ib_d]
    an_d, bn_d = np.asarray(an_d), np.asarray(bn_d)
    sa_d, sb_d = np.asarray(sa_d), np.asarray(sb_d)

    # host reference peel of the same normalized operands
    an_h = (a / sa_d) * 0.5
    ia_h = host_peel(an_h, s)
    bn_h = (b / sb_d) * 0.5
    ib_h = host_peel(bn_h, s)

    # --- probe 1: representation error of the device peel ----------------
    for label, sl, xn, host_sl in [("peel_A", ia_d, an_d, ia_h),
                                   ("peel_B", ib_d, bn_d, ib_h)]:
        recon = sum(sl[t].astype(np.float64) * 2.0 ** (-SLICE_BITS * (t + 1))
                    for t in range(s))
        err = np.abs(recon - xn).max()          # vs the DEVICE-stored xn
        # theoretical floor: dropped mantissa below s*q bits of 1/2-scaled
        print(json.dumps({"probe": label, "repr_err": float(err),
                          "budget": 2.0 ** (-SLICE_BITS * (s + 1)),
                          "platform": platform}), flush=True)
        # slice agreement with host peel (first diverging slice tells
        # where the emulated-f64 loop drifts)
        diverge = next((t for t in range(s)
                        if not np.array_equal(sl[t], host_sl[t])), None)
        mism = 0 if diverge is None else int(
            (sl[diverge] != host_sl[diverge]).sum())
        print(json.dumps({"probe": label + "_vs_host",
                          "first_diverging_slice": diverge,
                          "mismatches_there": mism,
                          "platform": platform}), flush=True)

    # --- probe 2: device dots+fold on HOST-exact slices -------------------
    want = host_recombine(ia_h, ib_h, s)

    def dev_dot(ia, ib):
        acc = None
        for d in range(s):
            ga = jnp.concatenate([ia[t] for t in range(d + 1)], axis=-1)
            gb = jnp.concatenate([ib[d - t] for t in range(d + 1)], axis=-2)
            p = oz._dot_i8(ga, gb)
            acc = oz._fold_group(acc, d, p)
        return acc

    got = jax.jit(dev_dot)(
        [jnp.asarray(x) for x in ia_h], [jnp.asarray(x) for x in ib_h])
    err = np.abs(np.asarray(got) - want).max() / max(np.abs(want).max(), 1e-30)
    print(json.dumps({"probe": "dots_fold_on_exact_slices",
                      "rel_err": float(err), "platform": platform}),
          flush=True)

    # --- probe 3: host-exact dot of the DEVICE-peeled slices --------------
    want_dev = host_recombine(ia_d, ib_d, s)
    full_host = an_h @ bn_h
    err = np.abs(want_dev - full_host).max() / max(np.abs(full_host).max(),
                                                   1e-30)
    print(json.dumps({"probe": "exact_dot_of_device_slices",
                      "rel_err_vs_true_product": float(err),
                      "platform": platform}), flush=True)


if __name__ == "__main__":
    main()
