#!/usr/bin/env python
"""Compile-time scaling of the trace-unrolled distributed factorization.

Round-1 review item 5: all distributed algorithms unroll the per-k loop at
trace time, so program size grows with the tile count nt; nothing showed
XLA compile time stays sane at BASELINE-scale tile counts (nt = 64-128).
This script AOT-compiles (``jax.jit(...).lower().compile()`` — no
execution) distributed Cholesky on the 8-virtual-device CPU mesh at a
sweep of nt, with and without the persistent compilation cache, and
reports trace time, compile time, and compiled program size.

Run:  python scripts/compile_scaling.py [--nt 16,32,64,128]
(self-configures the virtual CPU platform; results to stderr + one JSON
line to stdout for DESIGN.md).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nt", default="16,32,64,128")
    ap.add_argument("--nb", type=int, default=8,
                    help="tile size (compile cost depends on tile COUNT, "
                         "not tile size; small tiles keep tracing cheap)")
    ap.add_argument("--cache", default="")
    ap.add_argument("--mode", default="unrolled",
                    choices=("unrolled", "scan"),
                    help="step formulation: unrolled per-k trace or the "
                         "lax.scan'd uniform step (O(1) compile)")
    args = ap.parse_args()

    if not os.environ.get("_DLAF_COMPILE_SCALING_CHILD"):
        import subprocess

        from dlaf_tpu.tpu_info import cpu_subprocess_env

        env = cpu_subprocess_env(n_virtual_devices=8)
        env["_DLAF_COMPILE_SCALING_CHILD"] = "1"
        rc = subprocess.run([sys.executable] + sys.argv, env=env).returncode
        sys.exit(rc)

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    if args.cache:
        os.environ["DLAF_COMPILATION_CACHE_DIR"] = args.cache

    import numpy as np

    import dlaf_tpu.config as config
    from dlaf_tpu.algorithms.cholesky import (_build_dist_cholesky,
                                              _build_dist_cholesky_scan)
    from dlaf_tpu.comm.grid import Grid
    from dlaf_tpu.common.index2d import (GlobalElementSize, GridSize2D,
                                         RankIndex2D, TileElementSize)
    from dlaf_tpu.matrix.distribution import Distribution
    from dlaf_tpu.matrix.tiling import storage_tile_grid

    config.initialize()
    grid = Grid(2, 4)
    results = []
    for nt in [int(x) for x in args.nt.split(",")]:
        nb = args.nb
        n = nt * nb
        dist = Distribution(size=GlobalElementSize(n, n),
                            block_size=TileElementSize(nb, nb),
                            grid_size=GridSize2D(2, 4),
                            rank=RankIndex2D(0, 0),
                            source_rank=RankIndex2D(0, 0))
        sr, sc, _, _ = storage_tile_grid(dist)
        if args.mode == "scan":
            fn = _build_dist_cholesky_scan(dist, grid.mesh, "L")
        else:
            fn = _build_dist_cholesky(dist, grid.mesh, "L", use_pallas=False,
                                      pallas_interpret=True)
        x = jax.ShapeDtypeStruct((sr, sc, nb, nb), np.float64)
        # the timed lower/compile + memory_analysis plumbing is the
        # library's now (dlaf_tpu.obs.telemetry, ISSUE 7 satellite);
        # with DLAF_PROGRAM_TELEMETRY=1 each point also lands as a
        # program record in the DLAF_METRICS_PATH artifact
        from dlaf_tpu.obs import telemetry

        prog = telemetry.aot_compile(
            f"compile_scaling.{args.mode}", jax.jit(fn), x)
        size = int((prog.memory or {}).get("code", -1))
        row = {"nt": nt, "mode": args.mode,
               "trace_s": round(prog.trace_s, 2),
               "compile_s": round(prog.compile_s, 2), "code_bytes": size}
        results.append(row)
        log(f"nt={nt}: trace {prog.trace_s:.1f}s, compile "
            f"{prog.compile_s:.1f}s, "
            f"code {size / 1e6 if size > 0 else -1:.1f} MB")
    print(json.dumps({"platform": "cpu-mesh8", "nb": args.nb,
                      "cache": bool(args.cache), "rows": results}),
          flush=True)


if __name__ == "__main__":
    main()
