#!/usr/bin/env python
"""Offline summary of dlaf_tpu observability artifacts.

Two input shapes, auto-detected:

* a ``DLAF_METRICS_PATH`` JSON-lines artifact (``dlaf_tpu.obs`` schema) —
  prints per-span aggregates (count/total/mean, best derived GFlop/s from
  the structured records, no stdout scraping), the collective byte/count
  counters per (kind, axis) from the last metrics snapshot, and any
  captured log events;
* a ``--dlaf:profile-dir`` / ``DLAF_TRACE_DIR`` directory — reads the
  newest ``plugins/profile/<ts>/*.trace.json.gz`` (Chrome trace event
  format; written alongside the xplane since the span tracer enables
  ``create_perfetto_trace``) and prints, per process track (device vs
  host threads), the top-N ops by total duration. This is the instrument
  for deciding WHERE config #1's 0.2 s actually goes — per-op tunnel
  probes sit on the ~140 ms RTT floor and cannot (BASELINE.md round 4).
  The trace parsing is :mod:`dlaf_tpu.obs.devtrace`'s (ISSUE 14) —
  single owner, not a fork — and ``--jsonl merged.jsonl`` additionally
  prints the per-phase device-time attribution section (op classes per
  algorithm phase, measured overlap, coverage) for the trace joined to
  that artifact.

Usage: python scripts/profile_summary.py <profile_dir | metrics.jsonl> \\
           [top_n] [--jsonl merged.jsonl ...]
"""
import collections
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def newest_trace(root: str) -> str:
    """Kept as the documented entry point; the implementation moved to
    :func:`dlaf_tpu.obs.devtrace.newest_trace` (single parser owner)."""
    from dlaf_tpu.obs.devtrace import newest_trace as _newest

    return _newest(root)


def summarize_jsonl(path: str, top_n: int) -> None:
    """Aggregate a dlaf_tpu.obs JSONL artifact (schema: obs.sinks)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from dlaf_tpu.obs import read_records

    records = read_records(path)
    spans = [r for r in records if r.get("type") == "span"]
    snaps = [r for r in records if r.get("type") == "metrics"]
    logs = [r for r in records if r.get("type") == "log"]
    progs = [r for r in records if r.get("type") == "program"]

    agg = collections.defaultdict(lambda: {"count": 0, "total": 0.0,
                                           "best_gflops": None})
    for s in spans:
        a = agg[s.get("name", "?")]
        a["count"] += 1
        a["total"] += s.get("dur_s", 0.0)
        g = s.get("gflops")
        if isinstance(g, (int, float)) and \
                (a["best_gflops"] is None or g > a["best_gflops"]):
            a["best_gflops"] = g
    print(f"== spans ({len(spans)} records) ==")
    ranked = sorted(agg.items(), key=lambda kv: -kv[1]["total"])[:top_n]
    for name, a in ranked:
        gf = (f"  best {a['best_gflops']:8.1f} GFlop/s"
              if a["best_gflops"] is not None else "")
        print(f"  {a['total'] * 1e3:10.2f} ms  x{a['count']:<4d} "
              f"mean {a['total'] / a['count'] * 1e3:8.2f} ms  {name}{gf}")

    # per-rank view when the artifact carries rank-stamped records (the
    # %r per-rank convention, docs/observability.md): the table code is
    # obs.aggregate's — single owner, not a fork
    if any("rank" in r for r in spans):
        from dlaf_tpu.obs.aggregate import format_skew_table, rank_skew_rows

        print("\n== per-rank span skew ==")
        for line in format_skew_table(rank_skew_rows(records), top_n):
            print(f"  {line}")

    if progs:
        print(f"\n== program telemetry ({len(progs)} events) ==")
        # every site with ANY program event gets a row: the in-body
        # retrace counters (tridiag.secular_batched etc.) emit retrace
        # events with no compile record, and hiding them would hide the
        # very compile-cost tail they exist to surface
        by_site = collections.defaultdict(lambda: {"n": 0, "compile": 0.0,
                                                   "peak": None})
        retraces = collections.Counter(p.get("site", "?") for p in progs
                                       if p.get("event") == "retrace")
        for p in progs:
            a = by_site[p.get("site", "?")]
            if p.get("event") != "compile":
                continue
            a["n"] += 1
            a["compile"] += p.get("compile_s", 0.0) or 0.0
            peak = (p.get("hbm") or {}).get("peak")
            if peak is not None:
                a["peak"] = max(a["peak"] or 0.0, peak)
        for site, a in sorted(by_site.items(), key=lambda kv: -kv[1]["compile"]):
            peak = (f"  peak {a['peak'] / 1024**3:.2f}G"
                    if a["peak"] is not None else "")
            print(f"  {a['compile']:8.2f} s compile  x{a['n']:<3d} "
                  f"traces {retraces.get(site, a['n']):<3d} {site}{peak}")

    if any(r.get("type") == "accuracy" for r in records):
        # accuracy table code is obs.aggregate's — single owner, not a
        # fork (docs/accuracy.md)
        from dlaf_tpu.obs.aggregate import (accuracy_rows,
                                            format_accuracy_table)

        print("\n== accuracy (worst bound_ratio per rank) ==")
        for line in format_accuracy_table(accuracy_rows(records), top_n):
            print(f"  {line}")

    if any(r.get("type") == "autotune" for r in records):
        # decision-trail rendering is obs.aggregate's — single owner,
        # not a fork (docs/autotune.md)
        from dlaf_tpu.obs.aggregate import (autotune_rows,
                                            format_autotune_trail)

        print("\n== autotune decision trail ==")
        for line in format_autotune_trail(autotune_rows(records), top_n):
            print(f"  {line}")

    serve = [r for r in records if r.get("type") == "serve"]
    resil = [r for r in records if r.get("type") == "resilience"]
    if serve or resil:
        print("\n== serve / resilience ==")
        reqs = [r for r in serve if r.get("event") == "request"]
        disp = [r for r in serve if r.get("event") == "dispatch"]
        if disp:
            hits = sum(r.get("cache") == "hit" for r in disp)
            print(f"  {len(disp)} dispatches ({hits} cache hits), "
                  f"{len(reqs)} requests")
        if reqs:
            # shared quantile computation (obs.metrics.quantile — the
            # same numpy-linear estimator behind the SLO window gauges
            # and bench.py's arms), not another hand-rolled p99
            from dlaf_tpu.obs.metrics import quantile

            lat = [r.get("total_s", 0.0) for r in reqs]
            print(f"  request latency: mean {sum(lat) / len(lat) * 1e3:.2f}"
                  f" ms  p99 {quantile(lat, 0.99) * 1e3:.2f} ms")
        if resil:
            events = collections.Counter(r.get("event", "?") for r in resil)
            print("  resilience events: "
                  + ", ".join(f"{k}={v}" for k, v in sorted(events.items())))
        if reqs:
            # requests section (ISSUE 13): slowest trace IDs with their
            # stage breakdown + per-op percentiles — the join code is
            # obs.aggregate's (request_rows/format_request_table),
            # single owner, not a fork
            from dlaf_tpu.obs.aggregate import (format_request_table,
                                                request_rows)
            from dlaf_tpu.obs.metrics import quantile

            print("\n== requests (slowest first; obs.aggregate "
                  "--trace <id> for the waterfall) ==")
            for line in format_request_table(request_rows(records),
                                             top_n=5):
                print(f"  {line}")
            by_op = collections.defaultdict(list)
            for r in reqs:
                by_op[r.get("op", "?")].append(r.get("total_s", 0.0))
            for op in sorted(by_op):
                lat = by_op[op]
                qs = "  ".join(
                    f"p{int(q * 100)} {quantile(lat, q) * 1e3:.2f} ms"
                    for q in (0.5, 0.95, 0.99))
                print(f"  {op:<9s} ({len(lat)} reqs): {qs}")
        # queue depth / shed / expired / breaker state from the last
        # snapshot (the gauges Queue.stats() exports — single owner of
        # the semantics, this is just the offline view)
        if snaps:
            rows = [m for m in snaps[-1]["metrics"]
                    if m.get("name") in ("dlaf_serve_depth",
                                         "dlaf_serve_shed_total",
                                         "dlaf_deadline_exceeded_total",
                                         "dlaf_circuit_state")]
            for m in sorted(rows, key=lambda m: m["name"]):
                labels = ",".join(f"{k}={v}" for k, v in
                                  sorted(m.get("labels", {}).items()))
                val = m.get("value", 0)
                state = ""
                if m["name"] == "dlaf_circuit_state":
                    state = "  (" + {0: "closed", 1: "half_open",
                                     2: "open"}.get(int(val), "?") + ")"
                print(f"  {val:>10.0f}  {m['name']}{{{labels}}}{state}")

    if snaps:
        print("\n== counters (last snapshot) ==")
        for m in snaps[-1]["metrics"]:
            if m.get("kind") != "counter":
                continue
            labels = ",".join(f"{k}={v}" for k, v in
                              sorted(m.get("labels", {}).items()))
            print(f"  {m['value']:>16.0f}  {m['name']}{{{labels}}}")
    if logs:
        print(f"\n== logs ({len(logs)}) ==")
        for r in logs[:top_n]:
            print(f"  [{r.get('level')}] {r.get('logger')}: {r.get('msg')}")


def main():
    argv = sys.argv[1:]
    jsonls = []
    while "--jsonl" in argv:
        i = argv.index("--jsonl")
        if i + 1 >= len(argv):
            raise SystemExit(__doc__)
        jsonls.append(argv[i + 1])
        del argv[i:i + 2]
    if not argv:
        raise SystemExit(__doc__)
    root = argv[0]
    top_n = int(argv[1]) if len(argv) > 1 else 25
    if os.path.isfile(root) and not root.endswith((".json", ".json.gz")):
        summarize_jsonl(root, top_n)
        return
    # trace mode: the parsing/classification is obs.devtrace's (single
    # owner, not a fork); this CLI keeps the per-track output contract
    from dlaf_tpu.obs import devtrace

    path = root if os.path.isfile(root) else newest_trace(root)
    print(f"trace: {path}")
    events = devtrace.load_trace(path)

    for track, total, rows in devtrace.track_tables(events):
        print(f"\n== {track}: {total:.1f} ms total (sum of events) ==")
        for name, dur in rows[:top_n]:
            print(f"  {dur:10.2f} ms  {100 * dur / max(total, 1e-9):5.1f}%"
                  f"  {name[:100]}")

    if jsonls:
        # per-phase attribution (ISSUE 14): device op classes joined to
        # the artifact's span windows — report code is devtrace's
        from dlaf_tpu.obs.aggregate import merge_artifacts

        print("\n== device-time attribution (obs.devtrace) ==")
        try:
            report = devtrace.attribute(events, merge_artifacts(jsonls))
        except ValueError as e:
            print(f"  (unavailable: {e})")
            return
        for line in devtrace.format_report(report, top_n):
            print(f"  {line}")


if __name__ == "__main__":
    main()
