#!/usr/bin/env python
"""Offline summary of a jax.profiler trace — no tensorboard needed.

Reads the newest ``plugins/profile/<ts>/*.trace.json.gz`` (Chrome trace
event format; written alongside the xplane by ``--dlaf:profile-dir`` runs
since PhaseTimer enables ``create_perfetto_trace``) under the given
directory and prints, per process track (device vs host threads), the
top-N ops by total duration. This is the instrument for deciding WHERE
config #1's 0.2 s actually goes — per-op tunnel probes sit on the ~140 ms
RTT floor and cannot (BASELINE.md round 4).

Usage: python scripts/profile_summary.py <profile_dir> [top_n]
"""
import collections
import glob
import gzip
import json
import os
import sys


def newest_trace(root: str) -> str:
    cands = sorted(
        glob.glob(os.path.join(root, "**", "*.trace.json.gz"),
                  recursive=True) +
        glob.glob(os.path.join(root, "**", "perfetto_trace.json.gz"),
                  recursive=True),
        key=os.path.getmtime)
    if not cands:
        raise SystemExit(f"no *.trace.json.gz under {root}")
    # prefer the chrome trace over the perfetto one at equal recency (both
    # carry the events; the chrome one names processes in metadata events)
    chrome = [c for c in cands if not c.endswith("perfetto_trace.json.gz")]
    return (chrome or cands)[-1]


def main():
    root = sys.argv[1]
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 25
    path = newest_trace(root)
    print(f"trace: {path}")
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data["traceEvents"] if isinstance(data, dict) else data

    proc_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc_names[e.get("pid")] = e.get("args", {}).get("name", "")

    # complete events only (ph == "X": have a duration)
    by_track = collections.defaultdict(collections.Counter)
    track_total = collections.Counter()
    for e in events:
        if e.get("ph") != "X":
            continue
        pid = e.get("pid")
        track = proc_names.get(pid, f"pid{pid}")
        dur = e.get("dur", 0) / 1e3  # us -> ms
        by_track[track][e.get("name", "?")] += dur
        track_total[track] += dur

    for track, total in track_total.most_common():
        print(f"\n== {track}: {total:.1f} ms total (sum of events) ==")
        for name, dur in by_track[track].most_common(top_n):
            print(f"  {dur:10.2f} ms  {100 * dur / max(total, 1e-9):5.1f}%"
                  f"  {name[:100]}")


if __name__ == "__main__":
    main()
