#!/usr/bin/env python
"""Targeted hardware probe for the FIXED Pallas Ozaki kernels.

The 2026-07-31 sweep session ran the pre-fix kernels: the scalar-prefetch
syrk failed Mosaic AOT legalization and took the pallas cholesky variants
down with it. This probe times the rewritten kernels (predicated square
grid; static-index SMEM mode blocks) in isolation and then the full
config-#1 cholesky under ``ozaki_impl=pallas`` — the designated lever for
the trailing update, whose jnp form is bound by the per-shift int32
intermediates it writes to HBM.

Run only on an otherwise-idle container: host contention inflates the
fenced timings (observed: a concurrent pytest run cost config #1 ~8%).

Usage: python scripts/tpu_pallas_probe.py [out.json]
Each step is guarded; the results document is re-printed to stdout after
every step so a wedge keeps everything already measured.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from measure_common import best_time, log, peel, setup_env  # noqa: E402


def main():
    jax = setup_env()
    import jax.numpy as jnp

    import dlaf_tpu.config as config

    config.initialize()
    log(f"platform: {jax.devices()[0].platform}, devices: {jax.devices()}")
    results = {"platform": jax.devices()[0].platform, "kernels": {},
               "cholesky": {}}

    def emit():
        print(json.dumps(results, default=float), flush=True)

    from dlaf_tpu.tile_ops.pallas_ozaki import (fused_slice_product,
                                                fused_slice_syrk,
                                                masked_slice_product)

    # raw dot-route micro: is XLA's s8 dot actually MXU-native on this
    # hardware, or does the bf16 route (exact for 7-bit slices) win?
    rngd = np.random.default_rng(3)
    i8a = jnp.asarray(rngd.integers(-64, 65, (3840, 256)), jnp.int8)
    i8b = jnp.asarray(rngd.integers(-64, 65, (256, 3840)), jnp.int8)
    fl = 2 * 3840 * 3840 * 256
    for name, fn in [
            ("dot_s8", lambda x, y: jnp.matmul(
                x, y, preferred_element_type=jnp.int32)),
            ("dot_bf16", lambda x, y: jnp.matmul(
                x.astype(jnp.bfloat16), y.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32).astype(jnp.int32)),
            ("dot_bf16_native", lambda x, y: jnp.matmul(
                x.astype(jnp.bfloat16), y.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32))]:
        try:
            t = best_time(fn, i8a, i8b)
            results["kernels"][name] = {"t": t, "gflops": fl / t / 1e9}
            log(f"{name}: {t:.5f}s {fl / t / 1e9:.1f} GF/s")
        except Exception as e:
            log(f"{name} FAILED: {e!r}"[:300])
    emit()

    m, k = 3840, 256
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((m, k)))
    b = jnp.asarray(rng.standard_normal((k, m)))
    flops_syrk = m * m * k
    flops_mm = 2 * m * m * k

    for s in (8, 7):
        ia, _ = peel(a, s)
        ib, _ = peel(b.T, s)
        ibt = jnp.swapaxes(ib, -1, -2)
        for name, fn, args, fl in [
                (f"syrk_pallas_s{s}", lambda x: fused_slice_syrk(x), (ia,),
                 flops_syrk),
                (f"syrk_pallas_s{s}_bf16",
                 lambda x: fused_slice_syrk(x, dot="bf16"), (ia,),
                 flops_syrk),
                (f"matmul_pallas_s{s}",
                 lambda x, y: fused_slice_product(x, y), (ia, ibt), flops_mm),
                (f"matmul_pallas_s{s}_bf16",
                 lambda x, y: fused_slice_product(x, y, dot="bf16"),
                 (ia, ibt), flops_mm)]:
            try:
                t = best_time(fn, *args)
                results["kernels"][name] = {"t": t, "gflops": fl / t / 1e9}
                log(f"{name}: {t:.4f}s {fl / t / 1e9:.1f} GF/s")
            except Exception as e:
                log(f"{name} FAILED: {e!r}"[:600])
            emit()

    # the distributed trailing form (per-tile-pair predication)
    try:
        s = 8
        R = m // k
        ia, _ = peel(a, s)
        iat = ia.reshape(s, R, k, k)
        mode = jnp.asarray(np.tril(np.ones((R, R), np.int32)))
        t = best_time(lambda x, md: masked_slice_product(x, x, md), iat, mode)
        useful = (R * (R + 1) // 2) * (2 * k**3)
        results["kernels"]["masked_pallas_s8"] = {
            "t": t, "gflops": useful / t / 1e9}
        log(f"masked_pallas_s8: {t:.4f}s {useful / t / 1e9:.1f} GF/s")
    except Exception as e:
        log(f"masked_pallas_s8 FAILED: {e!r}"[:600])
    emit()

    # panel-chain probe: per-call probes through the tunnel are RTT-bound
    # (~140 ms floor, 2026-07-31 session) — chain ITERS dependent steps
    # inside ONE program and divide, resolving the in-program per-step
    # panel cost that bounds config #1's serial critical path
    try:
        from jax import lax

        from dlaf_tpu.tile_ops import mixed as mx

        nbp, iters = 256, 24
        rngp = np.random.default_rng(1)
        xs = rngp.standard_normal((nbp, nbp))
        spd = jnp.asarray(xs @ xs.T + nbp * np.eye(nbp))

        def chain(stepfn):
            def body(c, _):
                out = stepfn(c)
                # rebuild an SPD input from the factor so every iteration
                # depends on the last (a ~20us gemm vs ms-scale steps)
                del c
                return out @ jnp.swapaxes(out, -1, -2), None

            return jax.jit(lambda m: lax.scan(body, m, None, length=iters)[0])

        # gemm-only baseline; the normalize keeps the carry bounded over
        # the iterations (tril of an SPD matrix is not a Cholesky factor,
        # so an unnormalized rebuild would overflow by step ~10)
        gemm_chain = chain(lambda c: jnp.tril(c / jnp.max(jnp.abs(c))))
        probes = {
            "chain_gemm_baseline": gemm_chain,
            "chain_potrf_inv_refined":
                chain(lambda c: mx.potrf_inv_refined("L", c)[0]),
            "chain_potrf_native_f64":
                chain(lambda c: jnp.tril(lax.linalg.cholesky(c))),
            "chain_potrf_f32":
                chain(lambda c: lax.linalg.cholesky(
                    c.astype(jnp.float32)).astype(jnp.float64)),
        }
        for name, fn in probes.items():
            t = best_time(fn, spd)
            results["kernels"][name] = {"t_ms_per_step": t / iters * 1e3}
            log(f"{name}: {t / iters * 1e3:.3f} ms/step")
        # recursive gemm-only seed, IN-PROGRAM: the round-2 point probes
        # were tunnel-RTT-bound (~290 ms vs a ~150 ms floor) and could not
        # resolve the real per-step cost — the chain divides the RTT out.
        # Trace AFTER setting the knob (the seed choice is trace-time).
        os.environ["DLAF_MIXED_SEED"] = "recursive"
        config.initialize()
        try:
            fn = chain(lambda c: mx.potrf_inv_refined("L", c)[0])
            t = best_time(fn, spd)
            results["kernels"]["chain_potrf_inv_recursive_seed"] = {
                "t_ms_per_step": t / iters * 1e3}
            log(f"chain_potrf_inv_recursive_seed: {t / iters * 1e3:.3f} "
                "ms/step")
        finally:
            os.environ.pop("DLAF_MIXED_SEED", None)
            config.initialize()
    except Exception as e:
        log(f"panel chain probe failed: {e!r}"[:400])
    emit()

    # full config #1 under the pallas impl, with the miniapp's residual
    # check (the pallas fold carries ~48 bits; hardware must confirm the
    # factorization still meets the f64 algorithm budget before the knob
    # can be promoted) — shared protocol: measure_common.cholesky_arm
    from measure_common import cholesky_arm

    for impl, s, dot in (("pallas", 8, "int8"), ("pallas", 7, "int8"),
                         ("jnp", 7, "bf16"), ("jnp", 8, "bf16")):
        key = f"impl={impl},slices={s},dot={dot}"
        try:
            results["cholesky"][key] = cholesky_arm(
                impl, s, dot, source="tpu_pallas_probe")
        except Exception as e:
            log(f"cholesky {key} FAILED: {e!r}"[:600])
        emit()

    path = sys.argv[1] if len(sys.argv) > 1 else None
    if path:
        with open(path, "w") as f:
            json.dump(results, f, default=float)
        log(f"wrote {path}")


if __name__ == "__main__":
    main()
