#!/usr/bin/env python
"""Run-time premium of the telescoped scan builders vs unrolled, on the
8-virtual-device CPU mesh: distributed triangular solve + multiply and
distributed reduction_to_band (VERDICT r3 item 4 — done criterion is a
measured premium <= ~1.2x at nt=32, like Cholesky's 1.18x).

Run:  python scripts/dist_scan_premium.py [--nt 32] [--nb 16] [--runs 5]
Self-configures the virtual CPU platform; one JSON line to stdout.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "")
os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def bench(fn, runs):
    fn()  # compile + warm
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nt", type=int, default=32)
    ap.add_argument("--nb", type=int, default=16)
    ap.add_argument("--runs", type=int, default=5)
    ap.add_argument("--grid", default="2,4")
    args = ap.parse_args()

    import numpy as np
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    import dlaf_tpu.config as config
    from dlaf_tpu.algorithms.triangular import (triangular_multiply,
                                                triangular_solve)
    from dlaf_tpu.comm.grid import Grid
    from dlaf_tpu.common.index2d import TileElementSize
    from dlaf_tpu.eigensolver.back_transform import bt_reduction_to_band
    from dlaf_tpu.eigensolver.reduction_to_band import reduction_to_band
    from dlaf_tpu.matrix.matrix import Matrix

    n = args.nt * args.nb
    rng = np.random.default_rng(0)
    a_h = np.tril(rng.standard_normal((n, n))) + n * np.eye(n)
    b_h = rng.standard_normal((n, n))
    herm_h = rng.standard_normal((n, n))
    herm_h = (herm_h + herm_h.T) / 2
    gr, gc = (int(x) for x in args.grid.split(","))
    grid = Grid(gr, gc)
    ts = TileElementSize(args.nb, args.nb)

    out = {"nt": args.nt, "nb": args.nb, "grid": f"{gr}x{gc}", "cases": {}}
    for mode in ("unrolled", "scan"):
        os.environ["DLAF_DIST_STEP_MODE"] = mode
        config.initialize()
        am = Matrix.from_global(a_h, ts, grid=grid)
        bm = Matrix.from_global(b_h, ts, grid=grid)
        hm = Matrix.from_global(herm_h, ts, grid=grid)

        def run_solve():
            triangular_solve("L", "L", "N", "N", 1.0, am, bm) \
                .storage.block_until_ready()

        def run_mult():
            triangular_multiply("L", "L", "N", "N", 1.0, am, bm) \
                .storage.block_until_ready()

        def run_red2band():
            reduction_to_band(hm).matrix.storage.block_until_ready()

        red = reduction_to_band(hm)

        def run_bt_r2b():
            bt_reduction_to_band(red, bm).storage.block_until_ready()

        for name, fn in (("trsm_LLN", run_solve), ("trmm_LLN", run_mult),
                         ("red2band", run_red2band),
                         ("bt_r2b", run_bt_r2b)):
            t0 = time.perf_counter()
            t = bench(fn, args.runs)
            log(f"{mode} {name}: best {t*1e3:.1f} ms "
                f"(incl. compile {time.perf_counter()-t0:.1f} s)")
            out["cases"].setdefault(name, {})[mode] = t
    for name, d in out["cases"].items():
        d["premium"] = d["scan"] / d["unrolled"]
        log(f"{name}: premium {d['premium']:.2f}x")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
