#!/usr/bin/env python
"""Regenerate the MFU / roofline table in BASELINE.md.

Every perf PR so far reported bare GF/s; this script supplies the
*denominator*: a route-specific achievable ceiling per BASELINE config, so
results read as "% of route ceiling" (MFU) instead of unanchored numbers.

Ceilings are per chip and route-specific, not the marketing peak:

* **ozaki f64-equivalent** — the error-free int8-slice route spends
  ``s*(s+1)/2`` slice-pair dots per f64 product (s=7 on TPU: 28 — see
  ``config.f64_gemm_slices``), so the compute ceiling is
  ``dot-route peak / 28`` (bf16 path on TPU since the dot_ab session;
  bit-identical to the s8 dot).  A syrk-shaped trailing halves the
  mirrored pairs, so blocked factorizations can exceed ~½ of this model's
  denominator-pessimism — the ceiling is the honest matmul-pair model.
* **HBM roofline** — the jnp slice path is memory-bound well below the
  MXU ceiling at small N (the r4 sessions measured ~100x below raw dot
  peak); the traffic model below counts, per factorization step with
  trailing extent ``m``: 2 int8 slice operand sets (``2*s*m*nb`` bytes),
  one live int32 partial plane read+written and the f64 accumulator
  read+written under the scan accumulation schedule
  (``(4+4+8+8)*m**2``).  ``ceiling_hbm = flops / bytes * BW``.  This is
  an estimate of the *route's* traffic, stated so future PRs can refine
  it — not a measured counter.
* The **effective ceiling** per config is ``min(compute, HBM)``; the
  table's ``bound`` column names which side binds.

Measured values come from the append-only ``.bench_history.jsonl``
(post-peel-fix TPU f64 entries only — the pre-fix decomposition was
numerically corrupted; see bench.py ``PEEL_FIX_TS``).  Multi-chip
BASELINE configs whose grids this environment has never exposed report
their single-chip rehearsal number with a note, or "pending".

* **ICI roofline** (multi-chip configs) — comm-bound ceiling derived from
  the per-axis ``dlaf_comm_collective_bytes_total`` counters: the
  distributed program is TRACED (no compile, no execution) on a virtual
  CPU mesh of the config's grid in a subprocess — the UNROLLED builders,
  whose per-``k`` emission makes the trace-time counters exact per-run
  traffic (a scan body's counters fire once per traced body, not per
  executed iteration, and would undercount by the trip count) — the
  trace-time byte counters give the per-rank ICI payload per axis, and
  the ceiling is
  ``flops_model / sum_axis(2(p-1)/p * bytes_axis / link_bw)`` — the ring
  all-reduce traffic factor applied per mesh axis (conservative for the
  all_gathers, whose factor is (p-1)/p).  This is the bound the
  ``comm_lookahead`` overlap (docs/comm_overlap.md) must stay under even
  with perfect compute/comm overlap, so the "pending" multi-chip rows
  carry a number before live silicon does.  Link bandwidth is the public
  per-chip ICI aggregate / 4 links.

* **measured MFU (device)** — the ISSUE-14 measured path: entry-span
  flop models joined to the phase's attributed device-busy wall from a
  profiler trace (``dlaf_tpu.obs.devtrace``), replayed hermetically from
  the committed fixture under ``tests/fixtures/devtrace/`` (a distilled
  ``DLAF_TRACE_DIR`` Chrome trace + its merged JSONL). The denominator
  is measured device time, not host wall and not a model — but the
  committed fixture ran in the CPU CI container, so its numbers are
  labeled with their platform/shape and are NOT comparable to the TPU
  roofline ceilings; a TPU-captured fixture drops in with no code
  change.

Usage:
    python scripts/mfu_table.py            # print the markdown table
    python scripts/mfu_table.py --write    # splice into BASELINE.md
                                           # between the mfu-table markers
    python scripts/mfu_table.py --no-ici   # skip the traced ICI column
                                           # (fast; prints em-dashes)
    python scripts/mfu_table.py --measured # fill the measured(dev) and
                                           # measured-bound columns from
                                           # the committed devtrace and
                                           # critpath fixtures
    python scripts/mfu_table.py --reuse-ici  # reuse the ICI cells
                                           # already in BASELINE.md
                                           # instead of re-tracing
                                           # (hermetic regeneration)
    python scripts/mfu_table.py --fixture DIR  # override the devtrace
                                           # fixture dir
    python scripts/mfu_table.py --critpath-fixture DIR  # override the
                                           # critpath fixture dir
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
HISTORY = os.path.join(REPO, ".bench_history.jsonl")
BASELINE_MD = os.path.join(REPO, "BASELINE.md")
BEGIN, END = "<!-- mfu-table:begin -->", "<!-- mfu-table:end -->"

#: bench.py PEEL_FIX_TS — entries before it measured a corrupted
#: decomposition and must not feed the MFU table
PEEL_FIX_TS = "2026-08-02T04:00"

#: Public per-chip peaks. The measured platform is v5e (one chip via the
#: axon tunnel); v5p is the north-star target part.
CHIPS = {
    "v5e": dict(bf16=197e12, int8=394e12, hbm=819e9),
    "v5p": dict(bf16=459e12, int8=918e12, hbm=2765e9),
}

#: int8/bf16 slice-pair dots per f64 product at the TPU default
#: f64_gemm_slices=0 -> s=7 (config.py): s*(s+1)/2.
OZ_SLICES = 7
OZ_PAIRS = OZ_SLICES * (OZ_SLICES + 1) // 2

#: Per-link, per-direction ICI bytes/s: public per-chip aggregate (v5e
#: 1600 Gbps, v5p 4800 Gbps) spread over the 4 torus links.
ICI_LINK_BW = {"v5e": 50e9, "v5p": 150e9}

#: Reference real-flop models per family (the entry spans' total_ops
#: basis at real dtypes — add + mul summed — so the ICI ceiling divides
#: like the measured numbers do; config #3's complex weighting is noted
#: in its row, not folded in here).
#:
#: The three eigensolver-pipeline stage models (new in PR 6 — config #5
#: stops being a red2band proxy; docs/eigensolver_perf.md):
#:
#: * tridiag — D&C merge gemms: level l runs 2^l merges of size n/2^l,
#:   each blkdiag(q1, q2) @ qc ~ (n/2^l)^3 muls+adds -> sum = (4/3) n^3
#:   (deflation only reduces it, so this is the model ceiling).
#: * bt_b2t — chase back-transform: ~n^2/b reflectors of length b, each
#:   a rank-1 segment update of 2*b*m muls+adds over m = n columns
#:   -> 2 n^3.
#: * bt_r2b — reflector-block application C <- (I - V T V^H) C:
#:   W2 = V^H C and C -= V W2 at 2*b*m_p*n muls+adds each, summed over
#:   panels (sum m_p ~ n^2 / 2b) -> 2 n^3.
_FLOPS_MODEL = {
    "cholesky": lambda n: n ** 3 / 3,
    "trsm": lambda n: n ** 3,            # square B (free axis = n)
    "hegst": lambda n: n ** 3,
    "red2band": lambda n: 4 * n ** 3 / 3,
    "tridiag": lambda n: 4 * n ** 3 / 3,
    "bt_b2t": lambda n: 2 * n ** 3,
    "bt_r2b": lambda n: 2 * n ** 3,
    # full standard-EVP pipeline (the eigensolver entry span's canonical
    # 5n^3/3 muls + 5n^3/3 adds; #5's extra gen stages noted in its row)
    "eigensolver": lambda n: 10 * n ** 3 / 3,
}


def oz_compute_ceiling(chip: str, dot: str = "bf16") -> float:
    """f64-equivalent GF/s ceiling of the ozaki route on ``chip``."""
    return CHIPS[chip][dot] / OZ_PAIRS / 1e9


#: Modeled per-step panel-chain latency (seconds) of the CURRENT product
#: route: the 2026-08-01 v5e panel-chain probes measured the mixed
#: (f32-seed + Newton) potrf+trsm chain at ~+0.6 ms/step over pure gemm
#: at nb=256 (config.py ``f64_trsm`` docstring) — a latency- not
#: flops-bound figure, so it is held flat across the nb=256..512 configs
#: (a model, stated so future PRs can refine it with measured numbers).
#: The fused Pallas panel route (``panel_impl``, docs/pallas_panel.md)
#: replaces the chain with TWO kernel dispatches per step — modeled
#: ~0.1 ms/step pending silicon — which is the ~6x panel-ceiling lift
#: the ``fpanel`` / ``fpanel+fp1`` bench arms exist to measure.
PANEL_STEP_S = 0.6e-3

#: Modeled per-step latency of the FUSED STEP route (``step_impl``,
#: docs/pallas_panel.md): ONE pallas_call per blocked step — the panel
#: potrf, the strip solve, and the adjacent trailing slab never leave
#: VMEM between them, so the per-step floor collapses to a single kernel
#: dispatch + the strip's HBM streaming. Modeled ~0.05 ms/step pending
#: silicon (half the fused-panel chain's two dispatches) — the ``fstep``
#: bench arm and the committed critpath fixture pair
#: (tests/fixtures/critpath{,_prestep}/) are the measured instruments
#: that replace this model.
FUSED_STEP_S = 0.05e-3

#: Families whose per-step panel chain serializes across steps (step
#: k+1's panel consumes step k's strip): the chain is a WALL-CLOCK FLOOR
#: of nt * PANEL_STEP_S even under perfect lookahead/comm overlap, so
#: ``flops / floor`` is a hard ceiling like the rooflines.
_PANEL_CHAIN_FAMILIES = ("cholesky", "trsm", "hegst")


def panel_ceiling(family: str, n: int, nb: int,
                  step_s: float = PANEL_STEP_S):
    """Panel-critical-path ceiling in GF/s (steps x modeled panel
    latency), or None for families without a serialized per-step panel
    chain."""
    if family not in _PANEL_CHAIN_FAMILIES:
        return None
    nt = -(-n // nb)
    return _FLOPS_MODEL[family](n) / (nt * step_s) / 1e9


def chol_hbm_ceiling(chip: str, n: int, nb: int) -> float:
    """HBM-roofline GF/s for the blocked Cholesky's ozaki trailing path
    (traffic model in the module docstring; real-arithmetic flops n^3/3)."""
    flops = bytes_ = 0.0
    nt = -(-n // nb)
    for k in range(nt):
        m = n - (k + 1) * nb
        if m <= 0:
            continue
        flops += 2.0 * m * m * nb          # trailing herk/gemm adds+muls
        bytes_ += 2.0 * OZ_SLICES * m * nb + 24.0 * m * m
    if bytes_ == 0:
        return float("inf")
    return flops / bytes_ * CHIPS[chip]["hbm"] / 1e9


def trsm_hbm_ceiling(chip: str, n: int, nb: int) -> float:
    """Same traffic shape for the blocked substitution (free axis = n)."""
    return chol_hbm_ceiling(chip, n, nb)


def _trace_ici_child(spec: dict) -> None:
    """Child-process body (``--trace-ici``): trace the family's
    distributed builder on a virtual CPU mesh of the config's grid —
    abstract eval only, no compile/exec — and print the per-axis
    ``dlaf_comm_collective_bytes_total`` totals as JSON. Runs under
    ``tpu_info.cpu_subprocess_env`` so the device count can be forced."""
    sys.path.insert(0, REPO)
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from dlaf_tpu import obs
    from dlaf_tpu.comm.grid import Grid
    from dlaf_tpu.common.index2d import (GlobalElementSize, GridSize2D,
                                         TileElementSize)
    from dlaf_tpu.matrix.distribution import Distribution
    from dlaf_tpu.matrix.tiling import storage_tile_grid

    family = spec["family"]
    n, nb = spec["n"], spec["nb"]
    rows, cols = spec["rows"], spec["cols"]
    dtype = jnp.dtype(spec["dtype"])
    grid = Grid(rows, cols)
    dist = Distribution(GlobalElementSize(n, n), TileElementSize(nb, nb),
                        grid_size=GridSize2D(rows, cols))
    str_, stc, _, _ = storage_tile_grid(dist)
    sds = jax.ShapeDtypeStruct((str_, stc, nb, nb), dtype)

    def trace_red2band():
        from dlaf_tpu.eigensolver.reduction_to_band import \
            _build_dist_red2band

        fn = _build_dist_red2band(dist, grid.mesh, dtype.name,
                                  spec.get("band", nb))
        jax.eval_shape(fn, sds)

    def trace_bt_r2b():
        from dlaf_tpu.eigensolver.back_transform import _build_dist_bt_r2b

        band = spec.get("band", nb)
        npan = max(-(-n // band) - 1, 0)
        taus = jax.ShapeDtypeStruct((npan, band), dtype)
        fn = _build_dist_bt_r2b(dist, dist, grid.mesh, band, la=True)
        jax.eval_shape(fn, sds, taus, sds)

    def trace_bt_b2t():
        from dlaf_tpu.eigensolver.back_transform import _build_dist_bt_b2t

        band = spec.get("band", nb)
        n_sweeps = max(n - 2, 0)
        n_steps = -(-max(n - 1, 1) // band)
        fn = jax.jit(_build_dist_bt_b2t(dist, grid.mesh, b=band,
                                        cplx=False, n_sweeps=n_sweeps))
        jax.eval_shape(fn,
                       jax.ShapeDtypeStruct((n_sweeps, n_steps, band),
                                            dtype),
                       jax.ShapeDtypeStruct((n_sweeps, n_steps), dtype),
                       jax.ShapeDtypeStruct((n,), dtype), sds)

    # UNROLLED builders only: their per-k emission makes the trace-time
    # byte counters exact per-run traffic; a scan body traces once per
    # telescope segment and would undercount by the trip count.
    # (Exception: bt_b2t's layout all_to_alls sit OUTSIDE its sweep scan
    # — exactly two collectives per run — so its trace is exact too.)
    if family in ("cholesky",):
        from dlaf_tpu.algorithms.cholesky import _build_dist_cholesky

        fn = _build_dist_cholesky(dist, grid.mesh, "L", False, True)
        jax.eval_shape(fn, sds)
    elif family in ("trsm", "hegst"):
        from dlaf_tpu.algorithms.triangular import _build_dist_solve

        alpha = jax.ShapeDtypeStruct((), dtype)
        combos = ([("L", "L", "N")] if family == "trsm"
                  # twosolve HEGST = two whole-matrix solves
                  else [("L", "L", "N"), ("R", "L", "C")])
        for side, uplo, op in combos:
            fn = _build_dist_solve(dist, dist, grid.mesh, side, uplo,
                                   op, "N", dtype.name)
            jax.eval_shape(fn, sds, sds, alpha)
    elif family == "bt_r2b":
        trace_bt_r2b()
    elif family == "bt_b2t":
        trace_bt_b2t()
    elif family == "eigensolver":
        # the full pipeline's traced ICI traffic = red2band + both
        # back-transform stages (the counters accumulate across the three
        # traces); the host tridiag control stages move no ICI payload
        # and the sharded merge gemms communicate through GSPMD, which
        # the cc-layer counters do not see — noted in the #5 row
        trace_red2band()
        trace_bt_r2b()
        trace_bt_b2t()
    else:   # red2band
        trace_red2band()

    per_axis = {"row": 0.0, "col": 0.0}
    for m in obs.registry().snapshot():
        if m["name"] == "dlaf_comm_collective_bytes_total":
            axis = m["labels"].get("axis")
            if axis in per_axis:
                per_axis[axis] += m["value"]
    print(json.dumps(per_axis))


def ici_ceiling(family: str, n: int, nb: int, grid: str, chip: str):
    """Traced comm-bound ceiling in GF/s for a multi-chip config, or None
    (1x1 grids, the tridiag stage — its sharded merge gemms communicate
    through GSPMD collectives the cc-layer trace counters do not see —
    or the trace child failed)."""
    rows, cols = (int(x) for x in grid.split("x"))
    if rows * cols <= 1 or family == "tridiag":
        return None
    sys.path.insert(0, REPO)
    from dlaf_tpu.tpu_info import cpu_subprocess_env

    env = cpu_subprocess_env(n_virtual_devices=rows * cols)
    env["DLAF_METRICS_PATH"] = os.devnull   # arm the trace-time counters
    env.pop("DLAF_LOG", None)
    spec = dict(family=family, n=n, nb=nb, rows=rows, cols=cols,
                dtype="complex128" if family == "hegst" else "float64")
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--trace-ici",
             json.dumps(spec)],
            env=env, capture_output=True, text=True, timeout=2400,
            cwd=REPO, check=True)
        per_axis = json.loads(out.stdout.strip().splitlines()[-1])
    except (subprocess.SubprocessError, ValueError, OSError) as e:
        print(f"ici trace failed for {family} {n}/{nb} {grid}: {e}",
              file=sys.stderr)
        return None
    bw = ICI_LINK_BW[chip]
    t = 0.0
    for axis, p in (("row", rows), ("col", cols)):
        if p > 1 and per_axis.get(axis):
            t += 2.0 * (p - 1) / p * per_axis[axis] / bw
    if t == 0.0:
        return None
    return _FLOPS_MODEL[family](n) / t / 1e9


#: devtrace fixture for the measured-MFU column (``--measured``): a
#: distilled Chrome trace + merged JSONL, committed so the replay needs
#: no hardware and no live run (docs/observability.md device-time
#: attribution).
FIXTURE_DIR = os.path.join(REPO, "tests", "fixtures", "devtrace")

#: critpath fixture for the measured-bound column (``--measured``): the
#: ISSUE-16 per-step schedule join, committed with its schedule-bearing
#: merged artifact (docs/observability.md critical-path attribution).
CRITPATH_FIXTURE_DIR = os.path.join(REPO, "tests", "fixtures", "critpath")

#: critpath program (step-scope algo tag) -> table family.
ALGO_FAMILIES = {
    "cholesky": "cholesky", "trsm": "trsm", "hegst": "hegst",
    "red2band": "red2band", "bt_r2b": "bt_r2b",
}

#: entry-span phase name -> table family (the devtrace phase join keys
#: measured device GF/s by span name; the table rows key by family).
ENTRY_PHASE_FAMILIES = {
    "cholesky": "cholesky", "triangular_solve": "trsm",
    "gen_to_std": "hegst", "reduction_to_band": "red2band",
    "tridiag_solver": "tridiag", "bt_band_to_tridiag": "bt_b2t",
    "bt_reduction_to_band": "bt_r2b", "eigensolver": "eigensolver",
    "gen_eigensolver": "eigensolver",
}


def measured_device(fixture_dir: str = FIXTURE_DIR):
    """{family: "GF/s (platform n/nb grid)"} from the committed devtrace
    fixture — the device-busy-denominated measured numbers, labeled with
    where they ran so a CPU-container fixture can never masquerade as a
    TPU datum. Empty dict when the fixture is absent/unreadable (the
    column prints em-dashes)."""
    sys.path.insert(0, REPO)
    from dlaf_tpu.obs import devtrace
    from dlaf_tpu.obs.aggregate import merge_artifacts

    import glob as _glob

    trace = os.path.join(fixture_dir, "trace.json.gz")
    jsonls = sorted(_glob.glob(os.path.join(fixture_dir, "*.jsonl")))
    if not os.path.exists(trace) or not jsonls:
        return {}
    try:
        records = merge_artifacts(jsonls)
        report = devtrace.attribute(devtrace.load_trace(trace), records)
    except (OSError, ValueError) as e:
        print(f"mfu_table: devtrace fixture unreadable: {e}",
              file=sys.stderr)
        return {}
    platform = "cpu"
    for r in records:
        if r.get("type") == "accuracy" and r.get("platform"):
            platform = r["platform"]
            break
    attrs_by_name = {}
    for r in records:
        if r.get("type") == "span" and r.get("name"):
            attrs_by_name.setdefault(r["name"], r.get("attrs") or {})
    out = {}
    for phase, cell in report["phases"].items():
        family = ENTRY_PHASE_FAMILIES.get(phase)
        if family is None or "measured_gflops" not in cell:
            continue
        a = attrs_by_name.get(phase, {})
        label = (f"{cell['measured_gflops']:.2f} ({platform} "
                 f"{a.get('n', '?')}/{a.get('nb', '?')} "
                 f"{a.get('grid', '1x1')})")
        out[family] = label
    return out


def measured_bound(fixture_dir: str = CRITPATH_FIXTURE_DIR):
    """{family: "bound (platform n/nb grid)"} from the committed critpath
    fixture — the per-step critical-path classification's dominant bound
    (panel/bulk/comm/copy/gap), MEASURED from the schedule join instead of
    modeled from the panel-chain latency. Labeled with platform/shape like
    the measured(dev) column, for the same reason: a CPU-container
    fixture's bound (spin-wait collectives classify as comm) must never
    masquerade as a TPU datum. Empty dict when the fixture is
    absent/unreadable (the column prints em-dashes)."""
    sys.path.insert(0, REPO)
    from dlaf_tpu.obs import critpath, devtrace
    from dlaf_tpu.obs.aggregate import merge_artifacts

    import glob as _glob

    trace = os.path.join(fixture_dir, "trace.json.gz")
    jsonls = sorted(_glob.glob(os.path.join(fixture_dir, "*.jsonl")))
    if not os.path.exists(trace) or not jsonls:
        return {}
    try:
        records = merge_artifacts(jsonls)
        report = critpath.attribute(devtrace.load_trace(trace), records)
    except (OSError, ValueError) as e:
        print(f"mfu_table: critpath fixture unreadable: {e}",
              file=sys.stderr)
        return {}
    platform = "cpu"
    for r in records:
        if r.get("type") == "accuracy" and r.get("platform"):
            platform = r["platform"]
            break
    attrs_by_name = {}
    for r in records:
        if r.get("type") == "span" and r.get("name"):
            attrs_by_name.setdefault(r["name"], r.get("attrs") or {})
    out = {}
    for algo, prog in report["programs"].items():
        family = ALGO_FAMILIES.get(algo)
        if family is None or not prog.get("bound"):
            continue
        a = attrs_by_name.get(algo, {})
        out[family] = (f"{prog['bound']} ({platform} "
                       f"{a.get('n', '?')}/{a.get('nb', '?')} "
                       f"{a.get('grid', '1x1')})")
    return out


def parse_existing_ici(path: str = BASELINE_MD) -> dict:
    """{config label: ICI cell} parsed from the committed table — the
    ``--reuse-ici`` source, so a measured-column regeneration does not
    re-run the (minutes-long) trace subprocesses and stays hermetic."""
    try:
        with open(path) as f:
            doc = f.read()
    except OSError:
        return {}
    if BEGIN not in doc or END not in doc:
        return {}
    out = {}
    for line in doc[doc.index(BEGIN):doc.index(END)].splitlines():
        cells = [c.strip() for c in line.split("|")]
        # | config | route | compute | HBM | ICI | ... (leading '')
        if len(cells) >= 6 and cells[1].startswith("#"):
            out[cells[1]] = cells[5]
    return out


#: measured-entry classifier: history `variant` labels per workload family
_FAMILIES = {
    "cholesky": ("chol_", "ozaki", "scan", "xla", "loop", "biggemm",
                 "invgemm"),
    "trsm": ("trsm_",),
    "hegst": ("hegst_",),
    "red2band": ("red2band_",),
    "tridiag": ("tridiag",),       # bench.py dc arms: tridiag, tridiag+dcb1
    "bt_r2b": ("btr2b",),          # bench.py bt arms: btr2b, btr2b+btla1
    "bt_b2t": ("btb2t",),
    "eigensolver": ("eig_", "eigensolver"),
}


def measured(family: str, n: int, nb: int, path: str = HISTORY):
    """Best post-peel-fix TPU f64 GF/s for (family, n, nb), or None."""
    prefixes = _FAMILIES[family]
    best = None
    try:
        with open(path) as f:
            for raw in f:
                try:
                    r = json.loads(raw)
                except ValueError:
                    continue
                v = str(r.get("variant", ""))
                if not (r.get("platform") == "tpu"
                        and r.get("dtype") == "float64"
                        and r.get("n") == n and r.get("nb") == nb
                        and str(r.get("ts", "")) >= PEEL_FIX_TS
                        and isinstance(r.get("gflops"), (int, float))
                        and any(v.startswith(p) or v == p.rstrip("_")
                                for p in prefixes)):
                    continue
                if best is None or r["gflops"] > best:
                    best = r["gflops"]
    except OSError:
        return None
    return best


#: BASELINE configs + the measured single-chip config-#1 ladder. Fields:
#: (label, family, n, nb, grid, chip, note). ``n_meas``/``nb_meas``
#: override where the recorded number ran a rehearsal config.
CONFIGS = [
    ("#1 cholesky d 4096/256 1x1", "cholesky", 4096, 256, "1x1", "v5e", ""),
    ("#1 fused-step ceil 4096/256 1x1", "cholesky", 4096, 256, "1x1",
     "v5e", "panel ceiling at the fused STEP route's one-dispatch/step "
     "model (step_impl=fused, docs/pallas_panel.md) — the `fstep` bench "
     "arm + critpath fixture pair measure what this models"),
    ("#1 ladder 8192/256 1x1", "cholesky", 8192, 256, "1x1", "v5e", ""),
    ("#1 ladder 12288/256 1x1", "cholesky", 12288, 256, "1x1", "v5e", ""),
    ("#1 ladder 16384/256 1x1", "cholesky", 16384, 256, "1x1", "v5e", ""),
    ("#2 trsm d 8192/256 2x2", "trsm", 8192, 256, "2x2", "v5e",
     "single-chip local rehearsal (2x2 ICI unexposed); pre-peel-fix "
     "sessions recorded 128-131 GF/s — re-measure post-fix"),
    ("#3 hegst z 8192/256 2x2", "hegst", 8192, 256, "2x2", "v5e",
     "d-dtype twosolve rehearsal (tunnel lacks complex; z is CPU-mesh-"
     "verified)"),
    ("#4 red2band d 16384/512 4x4", "red2band", 16384, 512, "4x4", "v5e",
     "measured at 8192/512 single-chip; 16384 is multi-chip-only"),
    ("#5 gen_eigensolver d 32768/512 8x8", "eigensolver", 32768, 512,
     "8x8", "v5e", "standard-EVP 10n^3/3 model; ICI = traced red2band + "
     "both bt stages (tridiag GSPMD merge collectives + gen stages "
     "excluded); per-stage rows below"),
    # -- eigensolver-pipeline stage rows (configs #4-#5's trailing
    # stages; real flop/roofline models, not red2band proxies) ------------
    ("#5 stage tridiag d 32768/512", "tridiag", 32768, 512, "8x8", "v5e",
     "D&C merge gemms (4n^3/3 model ceiling — deflation reduces it); "
     "dc_level_batch batches each level's merges into one dispatch; "
     "sharded merges ride GSPMD, so no cc-traced ICI row"),
    ("#5 stage bt_band_to_tridiag d 32768/512", "bt_b2t", 32768, 512,
     "8x8", "v5e", "chase back-transform (2n^3): two layout all_to_alls "
     "around a local sweep scan — traced exactly"),
    ("#5 stage bt_reduction_to_band d 32768/512", "bt_r2b", 32768, 512,
     "8x8", "v5e", "reflector-block application (2n^3); bt_lookahead "
     "hoists each panel's gather ahead of the previous bulk "
     "(docs/eigensolver_perf.md)"),
]

#: where the recorded datum ran a different (n, nb) than the config asks
_MEAS_AT = {"#4 red2band d 16384/512 4x4": (8192, 512)}

#: rows whose panel-critical-path ceiling uses a different modeled
#: per-step latency than the product default (the fused-step ceiling row)
_STEP_S = {"#1 fused-step ceil 4096/256 1x1": FUSED_STEP_S}


def build_rows(with_ici=True, reuse_ici=None, dev=None, mb=None):
    rows = []
    dev = dev or {}
    mb = mb or {}
    for label, family, n, nb, grid, chip, note in CONFIGS:
        comp = oz_compute_ceiling(chip)
        hbm = (chol_hbm_ceiling(chip, n, nb)
               if family in ("cholesky", "trsm", "hegst") else None)
        if reuse_ici is not None:
            cell = reuse_ici.get(label, "—")
            try:
                ici = float(cell)
            except ValueError:
                ici = None
        elif with_ici:
            ici = ici_ceiling(family, n, nb, grid, chip)
        else:
            ici = None
        panel = panel_ceiling(family, n, nb,
                              step_s=_STEP_S.get(label, PANEL_STEP_S))
        candidates = [comp] + [x for x in (hbm, ici, panel)
                               if x is not None]
        ceil = min(candidates)
        bound = ("panel" if panel is not None and ceil == panel
                 else "ici" if ici is not None and ceil == ici
                 else "hbm" if hbm is not None and ceil == hbm else "mxu")
        n_m, nb_m = _MEAS_AT.get(label, (n, nb))
        got = measured(family, n_m, nb_m)
        mfu = f"{100.0 * got / ceil:.1f}%" if got else "—"
        rows.append((label, f"ozaki s={OZ_SLICES} (bf16 dots)",
                     f"{comp:.0f}", f"{hbm:.0f}" if hbm else "—",
                     f"{ici:.0f}" if ici else "—", bound,
                     f"{got:.1f}" if got else "pending",
                     dev.get(family, "—"), mb.get(family, "—"),
                     mfu, note))
    return rows


def render(with_ici=True, reuse_ici=None, dev=None, mb=None) -> str:
    head = (f"{BEGIN}\n"
            "## MFU / roofline table (scripts/mfu_table.py — regenerate "
            "with `--write`)\n\n"
            "Route ceilings per chip (f64-equivalent): ozaki compute = "
            f"dot-route peak / {OZ_PAIRS} slice pairs (s={OZ_SLICES}); "
            "HBM roofline from the slice-traffic model in the script "
            "docstring; ICI roofline (multi-chip rows) from the TRACED "
            "per-axis `dlaf_comm_collective_bytes_total` counters over "
            "per-link ICI bandwidth (ring traffic factor; script "
            "docstring) — the ceiling the `comm_lookahead` overlap "
            "(docs/comm_overlap.md) cannot exceed even with perfect "
            "compute/comm overlap. `MFU` = measured / min(compute, HBM, "
            "ICI). Measured values: best post-peel-fix TPU f64 entries "
            "in `.bench_history.jsonl` (v5e, one chip). Single-digit MFU "
            "with no roofline binding = the step chain is "
            "latency/serialization-bound — the gap `cholesky_lookahead` "
            "(docs/lookahead.md) + `comm_lookahead` exist to close; the "
            "N-ladder's rising MFU is that serial fraction amortizing. "
            "The #5 ICI bound sums the traced red2band + back-transform "
            "stage traffic; the `#5 stage` rows carry each trailing "
            "stage's own flop model and roofline (`dc_level_batch` / "
            "`bt_lookahead`, docs/eigensolver_perf.md), so config #5 "
            "reads per stage instead of through a red2band proxy. "
            "The panel-critical-path ceiling (step-chain families: flops "
            "/ (steps x modeled per-step panel-chain latency, "
            f"{PANEL_STEP_S * 1e3:.1f} ms from the 2026-08-01 probes)) "
            "stays folded into the ceiling min — `ceil bound = panel` "
            "still names it as the binding side, where the fused Pallas "
            "panel kernels (`panel_impl`, docs/pallas_panel.md) are the "
            "lever; the `#1 fused-step ceil` row re-prices that ceiling "
            "at the fused STEP route's one-dispatch-per-step model "
            f"({FUSED_STEP_S * 1e3:.2f} ms, `step_impl=fused` — the "
            "panel/strip/slab never round-trip HBM within a step), the "
            "headroom the `fstep` bench arm exists to claim — but its "
            "displayed column is replaced by `measured "
            "bound`: the ISSUE-16 per-step critical-path classification "
            "(`dlaf_tpu.obs.critpath`, docs/observability.md), the "
            "dominant per-step bound (panel/bulk/comm/copy/gap) measured "
            "from the schedule join over the committed "
            "`tests/fixtures/critpath/` fixture rather than modeled. "
            "Like `measured(dev)` it is labeled with the platform/shape "
            "it ran (the CI fixture is a CPU-container 2x2 run whose "
            "spin-wait collectives classify as comm-bound, and it "
            "carries the fixture's documented 2 ms synthetic step gap; "
            "a TPU-captured fixture drops in unchanged). "
            "`measured(dev)` is the ISSUE-14 device-timeline path "
            "(`dlaf_tpu.obs.devtrace` + `--measured`): entry-span flop "
            "models over the phase's attributed DEVICE-busy wall from a "
            "profiler trace — measured time, not a model — replayed "
            "hermetically from the committed "
            "`tests/fixtures/devtrace/` fixture and labeled with the "
            "platform/shape it ran (the CI fixture is a CPU-container "
            "2x2 run: its GF/s validate the measurement path, not the "
            "TPU ceilings; a TPU-captured fixture drops in unchanged — "
            "docs/observability.md device-time attribution).\n\n"
            "| config | route | compute ceil GF/s | HBM ceil GF/s "
            "| ICI ceil GF/s | ceil bound | measured GF/s "
            "| measured(dev) GF/s | measured bound | MFU | note |\n"
            "|---|---|---|---|---|---|---|---|---|---|---|\n")
    body = "".join("| " + " | ".join(r) + " |\n"
                   for r in build_rows(with_ici, reuse_ici, dev, mb))
    return head + body + END


def main() -> None:
    if "--trace-ici" in sys.argv:
        _trace_ici_child(json.loads(sys.argv[sys.argv.index("--trace-ici")
                                             + 1]))
        return
    fixture = FIXTURE_DIR
    if "--fixture" in sys.argv:
        i = sys.argv.index("--fixture") + 1
        if i >= len(sys.argv):
            raise SystemExit("mfu_table: --fixture needs a directory")
        fixture = sys.argv[i]
    cp_fixture = CRITPATH_FIXTURE_DIR
    if "--critpath-fixture" in sys.argv:
        i = sys.argv.index("--critpath-fixture") + 1
        if i >= len(sys.argv):
            raise SystemExit("mfu_table: --critpath-fixture needs a "
                             "directory")
        cp_fixture = sys.argv[i]
    dev = mb = None
    if "--measured" in sys.argv:
        dev = measured_device(fixture)
        mb = measured_bound(cp_fixture)
    reuse = parse_existing_ici() if "--reuse-ici" in sys.argv else None
    text = render(with_ici="--no-ici" not in sys.argv,
                  reuse_ici=reuse, dev=dev, mb=mb)
    if "--write" not in sys.argv:
        print(text)
        return
    with open(BASELINE_MD) as f:
        doc = f.read()
    if BEGIN in doc and END in doc:
        pre = doc[: doc.index(BEGIN)]
        post = doc[doc.index(END) + len(END):]
        doc = pre + text + post
    else:
        doc = doc.rstrip() + "\n\n" + text + "\n"
    with open(BASELINE_MD, "w") as f:
        f.write(doc)
    print(f"wrote MFU table into {BASELINE_MD}", file=sys.stderr)


if __name__ == "__main__":
    main()
