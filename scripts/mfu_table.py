#!/usr/bin/env python
"""Regenerate the MFU / roofline table in BASELINE.md.

Every perf PR so far reported bare GF/s; this script supplies the
*denominator*: a route-specific achievable ceiling per BASELINE config, so
results read as "% of route ceiling" (MFU) instead of unanchored numbers.

Ceilings are per chip and route-specific, not the marketing peak:

* **ozaki f64-equivalent** — the error-free int8-slice route spends
  ``s*(s+1)/2`` slice-pair dots per f64 product (s=7 on TPU: 28 — see
  ``config.f64_gemm_slices``), so the compute ceiling is
  ``dot-route peak / 28`` (bf16 path on TPU since the dot_ab session;
  bit-identical to the s8 dot).  A syrk-shaped trailing halves the
  mirrored pairs, so blocked factorizations can exceed ~½ of this model's
  denominator-pessimism — the ceiling is the honest matmul-pair model.
* **HBM roofline** — the jnp slice path is memory-bound well below the
  MXU ceiling at small N (the r4 sessions measured ~100x below raw dot
  peak); the traffic model below counts, per factorization step with
  trailing extent ``m``: 2 int8 slice operand sets (``2*s*m*nb`` bytes),
  one live int32 partial plane read+written and the f64 accumulator
  read+written under the scan accumulation schedule
  (``(4+4+8+8)*m**2``).  ``ceiling_hbm = flops / bytes * BW``.  This is
  an estimate of the *route's* traffic, stated so future PRs can refine
  it — not a measured counter.
* The **effective ceiling** per config is ``min(compute, HBM)``; the
  table's ``bound`` column names which side binds.

Measured values come from the append-only ``.bench_history.jsonl``
(post-peel-fix TPU f64 entries only — the pre-fix decomposition was
numerically corrupted; see bench.py ``PEEL_FIX_TS``).  Multi-chip
BASELINE configs whose grids this environment has never exposed report
their single-chip rehearsal number with a note, or "pending".

Usage:
    python scripts/mfu_table.py            # print the markdown table
    python scripts/mfu_table.py --write    # splice into BASELINE.md
                                           # between the mfu-table markers
"""

from __future__ import annotations

import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
HISTORY = os.path.join(REPO, ".bench_history.jsonl")
BASELINE_MD = os.path.join(REPO, "BASELINE.md")
BEGIN, END = "<!-- mfu-table:begin -->", "<!-- mfu-table:end -->"

#: bench.py PEEL_FIX_TS — entries before it measured a corrupted
#: decomposition and must not feed the MFU table
PEEL_FIX_TS = "2026-08-02T04:00"

#: Public per-chip peaks. The measured platform is v5e (one chip via the
#: axon tunnel); v5p is the north-star target part.
CHIPS = {
    "v5e": dict(bf16=197e12, int8=394e12, hbm=819e9),
    "v5p": dict(bf16=459e12, int8=918e12, hbm=2765e9),
}

#: int8/bf16 slice-pair dots per f64 product at the TPU default
#: f64_gemm_slices=0 -> s=7 (config.py): s*(s+1)/2.
OZ_SLICES = 7
OZ_PAIRS = OZ_SLICES * (OZ_SLICES + 1) // 2


def oz_compute_ceiling(chip: str, dot: str = "bf16") -> float:
    """f64-equivalent GF/s ceiling of the ozaki route on ``chip``."""
    return CHIPS[chip][dot] / OZ_PAIRS / 1e9


def chol_hbm_ceiling(chip: str, n: int, nb: int) -> float:
    """HBM-roofline GF/s for the blocked Cholesky's ozaki trailing path
    (traffic model in the module docstring; real-arithmetic flops n^3/3)."""
    flops = bytes_ = 0.0
    nt = -(-n // nb)
    for k in range(nt):
        m = n - (k + 1) * nb
        if m <= 0:
            continue
        flops += 2.0 * m * m * nb          # trailing herk/gemm adds+muls
        bytes_ += 2.0 * OZ_SLICES * m * nb + 24.0 * m * m
    if bytes_ == 0:
        return float("inf")
    return flops / bytes_ * CHIPS[chip]["hbm"] / 1e9


def trsm_hbm_ceiling(chip: str, n: int, nb: int) -> float:
    """Same traffic shape for the blocked substitution (free axis = n)."""
    return chol_hbm_ceiling(chip, n, nb)


#: measured-entry classifier: history `variant` labels per workload family
_FAMILIES = {
    "cholesky": ("chol_", "ozaki", "scan", "xla", "loop", "biggemm",
                 "invgemm"),
    "trsm": ("trsm_",),
    "hegst": ("hegst_",),
    "red2band": ("red2band_",),
    "eigensolver": ("eig_", "eigensolver"),
}


def measured(family: str, n: int, nb: int, path: str = HISTORY):
    """Best post-peel-fix TPU f64 GF/s for (family, n, nb), or None."""
    prefixes = _FAMILIES[family]
    best = None
    try:
        with open(path) as f:
            for raw in f:
                try:
                    r = json.loads(raw)
                except ValueError:
                    continue
                v = str(r.get("variant", ""))
                if not (r.get("platform") == "tpu"
                        and r.get("dtype") == "float64"
                        and r.get("n") == n and r.get("nb") == nb
                        and str(r.get("ts", "")) >= PEEL_FIX_TS
                        and isinstance(r.get("gflops"), (int, float))
                        and any(v.startswith(p) or v == p.rstrip("_")
                                for p in prefixes)):
                    continue
                if best is None or r["gflops"] > best:
                    best = r["gflops"]
    except OSError:
        return None
    return best


#: BASELINE configs + the measured single-chip config-#1 ladder. Fields:
#: (label, family, n, nb, grid, chip, note). ``n_meas``/``nb_meas``
#: override where the recorded number ran a rehearsal config.
CONFIGS = [
    ("#1 cholesky d 4096/256 1x1", "cholesky", 4096, 256, "1x1", "v5e", ""),
    ("#1 ladder 8192/256 1x1", "cholesky", 8192, 256, "1x1", "v5e", ""),
    ("#1 ladder 12288/256 1x1", "cholesky", 12288, 256, "1x1", "v5e", ""),
    ("#1 ladder 16384/256 1x1", "cholesky", 16384, 256, "1x1", "v5e", ""),
    ("#2 trsm d 8192/256 2x2", "trsm", 8192, 256, "2x2", "v5e",
     "single-chip local rehearsal (2x2 ICI unexposed); pre-peel-fix "
     "sessions recorded 128-131 GF/s — re-measure post-fix"),
    ("#3 hegst z 8192/256 2x2", "hegst", 8192, 256, "2x2", "v5e",
     "d-dtype twosolve rehearsal (tunnel lacks complex; z is CPU-mesh-"
     "verified)"),
    ("#4 red2band d 16384/512 4x4", "red2band", 16384, 512, "4x4", "v5e",
     "measured at 8192/512 single-chip; 16384 is multi-chip-only"),
    ("#5 gen_eigensolver d 32768/512 8x8", "eigensolver", 32768, 512,
     "8x8", "v5e", "pipeline rehearsal at 8192 passed; flops span mixed "
     "stages, MFU not meaningful as one number"),
]

#: where the recorded datum ran a different (n, nb) than the config asks
_MEAS_AT = {"#4 red2band d 16384/512 4x4": (8192, 512)}


def build_rows():
    rows = []
    for label, family, n, nb, grid, chip, note in CONFIGS:
        comp = oz_compute_ceiling(chip)
        hbm = (chol_hbm_ceiling(chip, n, nb)
               if family in ("cholesky", "trsm", "hegst") else None)
        ceil = min(comp, hbm) if hbm is not None else comp
        bound = "hbm" if (hbm is not None and hbm < comp) else "mxu"
        n_m, nb_m = _MEAS_AT.get(label, (n, nb))
        got = measured(family, n_m, nb_m)
        mfu = f"{100.0 * got / ceil:.1f}%" if got else "—"
        rows.append((label, f"ozaki s={OZ_SLICES} (bf16 dots)",
                     f"{comp:.0f}", f"{hbm:.0f}" if hbm else "—", bound,
                     f"{got:.1f}" if got else "pending", mfu, note))
    return rows


def render() -> str:
    head = (f"{BEGIN}\n"
            "## MFU / roofline table (scripts/mfu_table.py — regenerate "
            "with `--write`)\n\n"
            "Route ceilings per chip (f64-equivalent): ozaki compute = "
            f"dot-route peak / {OZ_PAIRS} slice pairs (s={OZ_SLICES}); "
            "HBM roofline from the slice-traffic model in the script "
            "docstring. `MFU` = measured / min(compute, HBM). Measured "
            "values: best post-peel-fix TPU f64 entries in "
            "`.bench_history.jsonl` (v5e, one chip). Single-digit MFU "
            "with neither roofline binding = the step chain is "
            "latency/serialization-bound — the gap `cholesky_lookahead` "
            "(docs/lookahead.md) exists to close; the N-ladder's rising "
            "MFU is that serial fraction amortizing.\n\n"
            "| config | route | compute ceil GF/s | HBM ceil GF/s | bound "
            "| measured GF/s | MFU | note |\n"
            "|---|---|---|---|---|---|---|---|\n")
    body = "".join("| " + " | ".join(r) + " |\n" for r in build_rows())
    return head + body + END


def main() -> None:
    text = render()
    if "--write" not in sys.argv:
        print(text)
        return
    with open(BASELINE_MD) as f:
        doc = f.read()
    if BEGIN in doc and END in doc:
        pre = doc[: doc.index(BEGIN)]
        post = doc[doc.index(END) + len(END):]
        doc = pre + text + post
    else:
        doc = doc.rstrip() + "\n\n" + text + "\n"
    with open(BASELINE_MD, "w") as f:
        f.write(doc)
    print(f"wrote MFU table into {BASELINE_MD}", file=sys.stderr)


if __name__ == "__main__":
    main()
