"""Localize the red2band residual-check failure (session 4d, 2026-08-01).

Observed: red2band under the product mxu knobs runs 51-107 GF/s on the v5e
but FAILS its eigenvalue check with a roughly size-independent residual
(1.07e-5 at n=4096, 5.3e-6 at n=8192, tol ~1e-8), while the identical
algorithm + knobs on CPU give 8e-16. A size-independent ~100x-f32-eps error
points at one under-precise building block, and the prime suspect is XLA's
``geqrf`` primitive (the panel-reflector factorization,
eigensolver/reduction_to_band.py) — the one primitive in the pipeline the
(check-passing) cholesky config never exercises.

Probes, each on device with f64 (= 2xf32 emulation on TPU):

1. ``geqrf`` backward error ||A - QR|| / ||A|| and orthogonality
   ||Q^T Q - I|| on random panels at red2band's shapes — measures the
   primitive in isolation.
2. closed-form ``larft`` T-factor consistency: the below-diagonal part of
   ``(I - V T V^T) A_panel`` must vanish — separates larft (and its
   ``triangular_solve``) from geqrf.
3. full red2band at n=2048, nb=512, band=128 on device, geqrf vs the new
   ``qr_panel=householder`` route — the end-to-end A/B: if householder
   PASSES the eigenvalue budget where geqrf FAILs, the primitive is
   convicted and the route flip is the fix.

Writes one JSON line per probe to stdout; run standalone on a healthy
tunnel (NOT concurrently with a session arm — HBM is shared).
"""

from __future__ import annotations

import json
import sys

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> None:
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    from jax._src.lax.linalg import geqrf

    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from dlaf_tpu.tile_ops.qr_panel import rebuild_q

    platform = jax.devices()[0].platform
    log(f"platform: {platform}")
    rng = np.random.default_rng(7)

    # --- probe 1: geqrf in isolation at red2band panel shapes ------------
    for (m, k) in [(1024, 128), (4096, 128), (8064, 128), (1024, 512)]:
        a = rng.standard_normal((m, k))
        av = jnp.asarray(a, dtype=jnp.float64)
        v, taus = jax.jit(geqrf)(av)
        v, taus = np.asarray(v), np.asarray(taus)
        r = np.triu(v[:k])
        q = rebuild_q(v, taus)   # host true-f64 oracle (shared helper)
        back = np.linalg.norm(a - q @ r) / np.linalg.norm(a)
        orth = np.linalg.norm(q.T @ q - np.eye(k))
        print(json.dumps({"probe": "geqrf", "m": m, "k": k,
                          "backward": float(back), "orth": float(orth),
                          "platform": platform}), flush=True)

    # --- probe 2: larft consistency with geqrf's reflectors -------------
    from dlaf_tpu.tile_ops.lapack import larft

    m, k = 1024, 128
    a = rng.standard_normal((m, k))
    av = jnp.asarray(a, dtype=jnp.float64)

    def panel_t(av):
        vfull, taus = geqrf(av)
        v = jnp.tril(vfull, -1) + jnp.eye(m, k, dtype=av.dtype)
        t = larft(v, taus)
        return vfull, taus, v, t

    vfull, taus, v, t = jax.jit(panel_t)(av)
    vn, tn = np.asarray(v), np.asarray(t)
    # (I - V T V^T) A should equal [R; 0] (the QR annihilation)
    applied = a - vn @ (tn @ (vn.T @ a))
    resid_below = np.linalg.norm(np.tril(applied, -1)) / np.linalg.norm(a)
    print(json.dumps({"probe": "larft_apply", "m": m, "k": k,
                      "below_band": float(resid_below),
                      "platform": platform}), flush=True)

    # --- probe 3: red2band end-to-end, geqrf vs householder panels ------

    from dlaf_tpu import config
    from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
    from dlaf_tpu.eigensolver.reduction_to_band import reduction_to_band
    from dlaf_tpu.matrix.matrix import Matrix

    n, nb, band = 2048, 512, 128

    def fn(i, j):
        return np.cos(0.001 * (i * 31 + j * 17)) + np.cos(0.001 * (j * 31 + i * 17))

    for route in ("geqrf", "householder"):
        os.environ["DLAF_QR_PANEL"] = route
        config.initialize()
        ref = Matrix.from_element_fn(fn, GlobalElementSize(n, n),
                                     TileElementSize(nb, nb),
                                     dtype=np.float64)
        red = reduction_to_band(ref, band_size=band)
        full = red.matrix.to_numpy()
        aref = ref.to_numpy()
        bd = np.zeros_like(aref)
        for rr in range(band + 1):
            d = np.diagonal(full, -rr)
            bd += np.diag(d, -rr)
            if rr:
                bd += np.diag(d.conj(), rr)
        w1 = np.linalg.eigvalsh(bd)
        w2 = np.linalg.eigvalsh(aref)
        resid = np.abs(w1 - w2).max() / max(np.abs(w2).max(), 1e-30)
        # how big is what the band extraction silently drops?
        dropped = np.linalg.norm(np.tril(full, -(band + 1)))
        print(json.dumps({"probe": f"red2band_n{n}_{route}",
                          "eig_resid": float(resid),
                          "dropped_below_band": float(dropped),
                          "platform": platform}), flush=True)
    del os.environ["DLAF_QR_PANEL"]


if __name__ == "__main__":
    main()
