#!/usr/bin/env python
"""Regenerate the committed device-trace fixtures in one command.

    python scripts/refresh_devtrace_fixture.py \
        [--only devtrace|critpath|critpath_prestep] [--no-inject]
        [--keep-tmp]

Three fixtures ship in the repo, all distilled from the same miniapp
configuration (2x2 cholesky, n=128 nb=32, lookahead + comm-lookahead,
XLA:CPU with 4 forced host devices):

* ``tests/fixtures/devtrace/`` — the device-timeline attribution fixture
  (``mfu_table.py --measured`` source, ISSUE 14).  Traced run without
  program telemetry; distilled by ``obs.devtrace --distill``.
* ``tests/fixtures/critpath/`` — the per-step critical-path fixture
  (ISSUE 16), run with the FUSED STEP route armed
  (``DLAF_STEP_IMPL=fused``, interpret mode on CPU — docs/pallas_panel
  .md "Fused step kernel").  Traced run WITH
  ``DLAF_PROGRAM_TELEMETRY=1`` so the merged artifact carries the
  ``schedule`` records the joiner needs, then a 2 ms synthetic gap is
  injected before ``cholesky.step002`` (``--no-inject`` skips it).  The
  injection is deliberate and documented: XLA:CPU collectives
  spin-wait, so a CPU-container run has genuinely ZERO device idle
  between steps — the committed fixture would otherwise exercise the
  gap-accounting path only at 0.0, and the replay tests could not pin
  "a known gap is recovered at the right boundary" hermetically.  The
  injected size/step are asserted below, so a refresh that drifts fails
  here, not in CI.
* ``tests/fixtures/critpath_prestep/`` — the SAME configuration and
  injection on the composed-op step route (``DLAF_STEP_IMPL=xla``):
  the fused step's committed A/B partner (ISSUE 19).  Same n/nb/grid,
  same documented injection; the pair difference isolates the step
  route, and both refresh legs print the per-step boundary-gap vector
  so the pair's boundary-gap accounting is recorded with the fixtures.

Both critpath legs run ``--type s`` (f32): the fused step kernel is
f32/bf16-only, and the A/B partner must match in everything but the
step route.  (The devtrace leg keeps the f64 default.)

Each leg ends with a hermetic self-check (replay the distilled fixture
exactly the way the tests and ``mfu_table.py``/CI do; validate the
record schema with the matching ``--require-*`` obligation) and only
then replaces the committed fixture.  Exit 0 = all requested fixtures
refreshed and verified.
"""

from __future__ import annotations

import gzip
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")
sys.path.insert(0, REPO)

#: One shared miniapp shape: small enough to distill to ~100 KB, deep
#: enough for a 4-step pipeline (nt = 128/32) on a 2x2 grid.
MINIAPP = ["-m", "128", "-b", "32", "--grid-rows", "2", "--grid-cols", "2",
           "--nruns", "2"]

#: The critpath fixture's documented synthetic gap (see module docstring).
INJECT_SPEC = "cholesky.step002=2.0"
INJECT_STEP = 2
INJECT_S = 2.0e-3

BASE_ENV = {
    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
    "JAX_PLATFORMS": "cpu",
    "DLAF_CHOLESKY_LOOKAHEAD": "1",
    "DLAF_COMM_LOOKAHEAD": "1",
}


def run(cmd, env=None, **kw):
    merged = dict(os.environ)
    merged.update(env or {})
    print("+", " ".join(cmd), flush=True)
    return subprocess.run(cmd, env=merged, cwd=REPO, check=True, **kw)


def traced_miniapp(tmp: str, telemetry: bool,
                   step_impl: str | None = None) -> tuple[str, str]:
    """Run the traced miniapp; return (trace_dir, merged_jsonl)."""
    os.makedirs(tmp, exist_ok=True)
    art = os.path.join(tmp, "art")
    trace_dir = os.path.join(tmp, "trace")
    merged = os.path.join(tmp, "merged.jsonl")
    env = dict(BASE_ENV, DLAF_METRICS_PATH=art, DLAF_TRACE_DIR=trace_dir)
    extra = []
    if telemetry:
        env["DLAF_PROGRAM_TELEMETRY"] = "1"
    if step_impl is not None:
        env["DLAF_STEP_IMPL"] = step_impl
        # the fused step kernel is f32/bf16-only; BOTH critpath legs run
        # f32 so the pair's only difference is the step route
        extra = ["--type", "s"]
    run([sys.executable, "-m", "dlaf_tpu.miniapp.miniapp_cholesky",
         *MINIAPP, *extra], env=env)
    run([sys.executable, "-m", "dlaf_tpu.obs.aggregate", art, "-o", merged])
    return trace_dir, merged


def refresh_devtrace(tmp: str) -> None:
    from dlaf_tpu.obs import devtrace
    from dlaf_tpu.obs.aggregate import merge_artifacts
    from dlaf_tpu.obs.sinks import DEVTRACE_COVERAGE_FLOOR, validate_records

    trace_dir, merged = traced_miniapp(os.path.join(tmp, "dev"),
                                       telemetry=False)
    distilled = os.path.join(tmp, "dev", "trace.json.gz")
    run([sys.executable, "-m", "dlaf_tpu.obs.devtrace", trace_dir, merged,
         "--distill", distilled], stdout=subprocess.DEVNULL)
    # hermetic self-check: exactly the replay the tests and mfu_table do
    records = merge_artifacts([merged])
    report = devtrace.attribute(devtrace.load_trace(distilled), records)
    assert report["join"] == "annotation", report["join"]
    assert report["coverage"] >= DEVTRACE_COVERAGE_FLOOR, report["coverage"]
    assert report["overlap"], "no attributed collectives"
    assert "cholesky" in report["phases"], sorted(report["phases"])
    recs = devtrace.records_from_report(report, distilled)
    errs = validate_records(records + recs, require_devtrace=True)
    assert not errs, errs
    dest = os.path.join(FIXTURES, "devtrace")
    os.makedirs(dest, exist_ok=True)
    shutil.copy(distilled, os.path.join(dest, "trace.json.gz"))
    shutil.copy(merged, os.path.join(dest, "merged.jsonl"))
    print(f"devtrace fixture refreshed -> {dest} "
          f"(coverage {report['coverage']:.1%})")


def refresh_critpath(tmp: str, inject: bool, step_impl: str = "fused",
                     dest_name: str = "critpath") -> None:
    from dlaf_tpu.obs import critpath, devtrace
    from dlaf_tpu.obs.aggregate import merge_artifacts
    from dlaf_tpu.obs.sinks import CRITPATH_COVERAGE_FLOOR, validate_records

    trace_dir, merged = traced_miniapp(
        os.path.join(tmp, "cp_" + dest_name), telemetry=True,
        step_impl=step_impl)
    records = merge_artifacts([merged])
    events = devtrace.load_trace(trace_dir)
    if inject:
        algo, step, seconds = critpath.parse_inject(INJECT_SPEC)
        n = critpath.inject_gap(events, records, algo, step, seconds)
        assert n >= 1, "injection found no runs"
        print(f"injected {seconds * 1e3:.1f} ms before "
              f"{algo}.step{step:03d} in {n} runs (documented synthetic "
              "gap: XLA:CPU spin-wait collectives leave zero real idle)")
    kept = devtrace.distill(events, records)
    distilled = os.path.join(tmp, "cp_" + dest_name, "trace.json.gz")
    with gzip.open(distilled, "wt", encoding="utf-8") as fh:
        fh.write(json.dumps({"traceEvents": kept}))
    # hermetic self-check: the replay CI and the tests perform
    replay = critpath.attribute(devtrace.load_trace(distilled), records)
    assert replay["coverage"] >= CRITPATH_COVERAGE_FLOOR, replay["coverage"]
    prog = replay["programs"]["cholesky"]
    assert prog["n_steps"] >= 2, prog["n_steps"]
    assert all(s.get("bound") for s in prog["steps"]
               if not s.get("empty")), "steps without bound class"
    if inject:
        gap = prog["steps"][INJECT_STEP - 1].get("gap_after_s", 0.0)
        # lookahead overlap eats into the boundary; at least half the
        # injected idle must be recovered at the RIGHT boundary on the
        # composed route.  The fused step's single long kernel spans the
        # boundary and absorbs most of the stall (the pair's measured
        # gap-shrink claim, docs/pallas_panel.md "Fused step kernel") —
        # its floor only pins that the residual stays attributable.
        floor = (0.5 if step_impl != "fused" else 0.1) * INJECT_S
        assert gap >= floor, (
            f"injected gap not recovered: {gap * 1e3:.3f} ms before "
            f"step{INJECT_STEP:03d} (floor {floor * 1e3:.3f} ms)")
        others = [s.get("gap_after_s", 0.0) for s in prog["steps"]
                  if not s.get("empty") and s["step"] != INJECT_STEP - 1]
        assert all(g < gap for g in others), (gap, others)
    recs = critpath.records_from_report(replay, distilled)
    errs = validate_records(records + recs, require_critpath=True)
    assert not errs, errs
    dest = os.path.join(FIXTURES, dest_name)
    os.makedirs(dest, exist_ok=True)
    shutil.copy(distilled, os.path.join(dest, "trace.json.gz"))
    shutil.copy(merged, os.path.join(dest, "merged.jsonl"))
    gap_ms = (prog["steps"][INJECT_STEP - 1].get("gap_after_s", 0.0) * 1e3
              if inject else 0.0)
    gaps = [round(s.get("gap_after_s", 0.0) * 1e3, 3)
            for s in prog["steps"] if not s.get("empty")]
    print(f"{dest_name} fixture refreshed -> {dest} "
          f"(step_impl={step_impl}, coverage {replay['coverage']:.1%}, "
          f"gap before step{INJECT_STEP:03d}: {gap_ms:.3f} ms, "
          f"boundary gaps/ms: {gaps})")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    only = None
    inject = True
    keep = False
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--only":
            i += 1
            only = argv[i]
            if only not in ("devtrace", "critpath", "critpath_prestep"):
                print("--only must be devtrace|critpath|critpath_prestep, "
                      f"got {only!r}", file=sys.stderr)
                return 2
        elif a == "--no-inject":
            inject = False
        elif a == "--keep-tmp":
            keep = True
        else:
            print(__doc__, file=sys.stderr)
            return 2
        i += 1
    tmp = tempfile.mkdtemp(prefix="fixture_refresh_")
    try:
        if only in (None, "devtrace"):
            refresh_devtrace(tmp)
        if only in (None, "critpath_prestep"):
            refresh_critpath(tmp, inject, step_impl="xla",
                             dest_name="critpath_prestep")
        if only in (None, "critpath"):
            refresh_critpath(tmp, inject)
    finally:
        if keep:
            print(f"scratch kept: {tmp}")
        else:
            shutil.rmtree(tmp, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
