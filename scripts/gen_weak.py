#!/usr/bin/env python
"""Weak-scaling campaign generator.

TPU-native counterpart of the reference's ``scripts/gen_weak.py``: fixed
work per device — N grows with sqrt(devices) so the per-device tile count is
constant.

Usage: python scripts/gen_weak.py --miniapp cholesky --m-per-device 8192 \
           -b 512 --grids 1x1 2x2 4x4 > weak.sh
"""

import argparse
import math

from gen_strong import MINIAPPS


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--miniapp", choices=MINIAPPS, default="cholesky")
    p.add_argument("--m-per-device", type=int, default=8192)
    p.add_argument("-b", type=int, default=512)
    p.add_argument("--grids", nargs="+", default=["1x1", "2x2", "4x4"])
    p.add_argument("--nruns", type=int, default=5)
    p.add_argument("--type", default="d")
    p.add_argument("--dlaf", nargs="*", default=[],
                   help="extra --dlaf:<knob>=<value> options appended to "
                        "every command (e.g. dist-step-mode=scan)")
    args = p.parse_args()
    extra = "".join(f" --dlaf:{o}" for o in args.dlaf)
    mod = MINIAPPS[args.miniapp]
    print("#!/bin/sh")
    print(f"# weak scaling: {args.miniapp} m/device={args.m_per_device}")
    for g in args.grids:
        r, c = (int(x) for x in g.split("x"))
        n = int(args.m_per_device * math.sqrt(r * c))
        n = (n // args.b) * args.b or args.b
        print(f"python -m {mod} -m {n} -b {args.b} --grid-rows {r} "
              f"--grid-cols {c} --nruns {args.nruns} --type {args.type}"
              f"{extra}")


if __name__ == "__main__":
    main()
