#!/usr/bin/env python
"""CI bench-regression gate over the append-only measurement history.

    python scripts/bench_gate.py --replay                  # CI smoke mode
    python scripts/bench_gate.py --fresh obs_artifact.jsonl [...]

Compares fresh measurements against a noise-aware baseline derived from
the git-tracked ``.bench_history.jsonl`` (121+ entries; the trajectory
BASELINE.md cites). Per key ``(variant, platform, n, nb, workload,
dtype)``:

* **baseline** = median of the ``--best-k`` (default 3) best historical
  GFlop/s — median-of-best, so one lucky outlier cannot ratchet the bar
  and one slow wedge-window entry cannot lower it;
* **fresh**    = the best GFlop/s among the new measurements for that
  key (matching bench.py's own best-of-reps protocol);
* **regression** iff ``fresh < (1 - tolerance) * baseline`` (default
  tolerance 0.10 — an injected 20 % slowdown must trip the gate, run-
  to-run noise must not);
* keys with fewer than ``--min-history`` (default 3) historical entries
  are **report-only**: a new benchmark arm needs a few rounds of history
  before it can gate anyone.

Fresh measurements come from ``--fresh`` files — obs JSONL artifacts
whose ``bench_result`` records carry the measurement payload (bench.py's
per-variant artifacts), or bare history-style line files. ``--replay``
instead replays the history's own best entry per key as the fresh
measurement — the hermetic CI mode: clean history must exit 0, and
``--inject-slowdown 0.2`` (the synthetic-regression drill ci/run.sh
smoke runs) must exit 1, proving the gate would catch a real 20 % loss.

The history is schema-validated first (``dlaf_tpu.obs.sinks`` history
schema — the ``--history`` mode of the validator CLI): a malformed or
non-finite line fails the gate loudly instead of skewing a baseline.

``workload="serve"`` lines (bench.py's serving arm, docs/serving.md)
additionally face a HISTORY-FREE absolute leg: their batched-vs-
loop-of-singles ``speedup`` field must be >= ``--min-serve-speedup``
(default 3.0 — the ISSUE-11 acceptance floor). Like accuracy_gate's
analytic-budget leg, this gates a brand-new serve measurement before
any history accumulates, and a committed serve history line keeps the
floor enforced in every ``--replay``.

``workload="autotune"`` lines (bench.py's accuracy-steered precision
arm, ISSUE 15, docs/autotune.md) face the analogous history-free leg:
their learned-table vs pinned-worst-case-route ``speedup`` field must
be >= ``--min-autotune-speedup`` (default 0.5 — parity minus
probe-per-call overhead on platforms where the ladder is inert; on TPU
the learned routes sit well above 1).

``workload="fstep"`` lines (bench.py's fused-step A/B arm, ISSUE 19,
docs/pallas_panel.md "Fused step kernel") face a history-free
COMPLETENESS leg: the pair is the claim — when any fstep line is fresh,
both the pinned composed-chain arm (``fstep``) and the fused-step arm
(``fstep+fs1``) must be present, so a half-pair cannot pass as an A/B.

``workload="fleet"`` lines (bench.py's multi-replica serve-tier arm,
ISSUE 18, docs/fleet.md) carry the third history-free leg: their
N-replica vs 1-replica requests/s ``speedup`` field must be >=
``--min-fleet-scaling`` (default 0.8 — the single-threaded router's
wire serialization bounds toy-size CPU scaling at parity-ish; the
floor trips routing collapse, not transport physics).

Exit status: 0 = no regression; 1 = regression (or invalid history /
no usable fresh measurements); 2 = usage error.
"""

from __future__ import annotations

import argparse
import math
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dlaf_tpu.obs.sinks import (read_history_records, read_records,
                                validate_history_line)


def worst_step_category(paths) -> str | None:
    """The largest per-step category wall (incl. step-boundary gaps)
    summed across the fresh artifacts' ``critpath`` records, as a human
    line, or None when no artifact carries them. Delegates the
    ``<algo>.stepNNN <category>`` vocabulary to ``perf_diff.extract`` —
    single owner — so the verdict and the explainer name steps
    identically."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from perf_diff import extract
    except ImportError:
        return None
    acc: dict = {}
    for p in paths:
        try:
            facts = extract(read_records(p))
        except (OSError, ValueError):
            continue
        for lbl, v in facts["step_cat"].items():
            acc[lbl] = acc.get(lbl, 0.0) + v
    if not acc:
        return None
    lbl, v = max(acc.items(), key=lambda kv: kv[1])
    return f"{lbl} ({v * 1e3:.2f} ms)"


def measurement_key(line: dict) -> tuple:
    """The baseline key: (variant, platform, n, nb, workload, dtype).
    The ISSUE-7 5-tuple plus dtype — a float32 arm must never gate a
    float64 baseline (different flop weights, same label otherwise)."""
    return (line.get("variant"), line.get("platform"), line.get("n"),
            line.get("nb"), line.get("workload") or "cholesky",
            line.get("dtype"))


def fmt_key(key: tuple) -> str:
    variant, platform, n, nb, workload, dtype = key
    wl = "" if workload == "cholesky" else f" workload={workload}"
    return f"{variant} [{platform}] n={n} nb={nb} {dtype}{wl}"


def load_fresh(paths) -> list:
    """Measurement lines from ``--fresh`` files: ``bench_result`` records
    of obs artifacts (payload = the measurement line), or bare
    history-style lines. Invalid lines are rejected loudly."""
    fresh = []
    for path in paths:
        for r in read_records(path):
            if not isinstance(r, dict):
                raise ValueError(f"{path}: non-object record")
            line = r.get("payload") if r.get("type") == "bench_result" else \
                (r if "gflops" in r and "type" not in r else None)
            if line is None:
                continue        # spans/metrics/logs ride along in artifacts
            errors = validate_history_line(line)
            if errors:
                raise ValueError(f"{path}: invalid fresh measurement: "
                                 + "; ".join(errors))
            fresh.append(line)
    return fresh


def baselines(history, best_k: int) -> dict:
    """{key: (baseline gflops, n_history)} — median of the best_k best."""
    per_key: dict = {}
    for line in history:
        per_key.setdefault(measurement_key(line), []).append(line["gflops"])
    return {key: (statistics.median(sorted(vals, reverse=True)[:best_k]),
                  len(vals))
            for key, vals in per_key.items()}


DEFAULT_MIN_SERVE_SPEEDUP = 3.0

#: History-free floor on the autotune arm's learned-table vs pinned-
#: worst-case-route speedup (ISSUE 15): the learned routes must never
#: cost more than this fraction of the conservative route's throughput.
#: On CPU every ladder rung is behavior-inert, so the honest expectation
#: is parity minus probe overhead — and at the arm's toy sizes the
#: O(n^2 k) probe is a real fraction of the O(n^3) factor (measured
#: ~0.7-0.8x at n=192-512 with probe-per-call; DLAF_AUTOTUNE_PROBE_EVERY
#: amortizes it in production). 0.5 trips a pathological steering loop
#: without tripping probe arithmetic; on TPU the learned routes are the
#: whole point and sit well above 1.
DEFAULT_MIN_AUTOTUNE_SPEEDUP = 0.5

#: History-free floor on the fleet arm's N-replica vs 1-replica
#: requests/s ratio (ISSUE 18, docs/fleet.md). The single-threaded
#: router serializes every request onto the wire, so at the arm's toy
#: CPU sizes the bound is protocol cost, not compute — the honest
#: expectation there is parity-ish (measured 1.03-1.09x at n=64-128,
#: 3 replicas). 0.8 trips the real failure modes — every bucket
#: hash-colliding onto one replica, failover thrash re-dispatching the
#: steady state — without demanding scaling the transport can't give;
#: on TPU-class program runtimes the replicas' parallel compute is the
#: point and the ratio sits well above 1.
DEFAULT_MIN_FLEET_SCALING = 0.8


def _best_speedup_per_key(fresh, workload: str) -> dict:
    """Best finite ``speedup`` field per key among ``workload`` lines —
    the bench protocol is best-of, so one slow pass must not trip a key
    whose best pass cleared the bar."""
    best: dict = {}
    for line in fresh:
        if line.get("workload") != workload:
            continue
        s = line.get("speedup")
        if not isinstance(s, (int, float)) or isinstance(s, bool) \
                or not math.isfinite(s):
            continue
        key = measurement_key(line)
        if key not in best or s > best[key]:
            best[key] = float(s)
    return best


def run_gate(history, fresh, *, tolerance: float, min_history: int,
             best_k: int, log=print,
             min_serve_speedup: float = DEFAULT_MIN_SERVE_SPEEDUP,
             min_autotune_speedup: float
             = DEFAULT_MIN_AUTOTUNE_SPEEDUP,
             min_fleet_scaling: float = DEFAULT_MIN_FLEET_SCALING) -> int:
    """Compare fresh bests against history baselines; returns the number
    of regressed keys. Keys without fresh measurements are skipped (the
    gate judges what this run measured, not what it skipped — bench.py's
    budget/wedge handling legitimately drops arms); keys with thin
    history are report-only.

    ``workload="serve"`` lines additionally carry the ISSUE-11 absolute
    floor: the batched-vs-loop-of-singles ``speedup`` field (bench.py's
    serve arm) must be >= ``min_serve_speedup`` — this leg is
    history-free (like accuracy_gate's analytic-budget leg), so a
    first-round serve measurement already gates."""
    base = baselines(history, best_k)
    fresh_best: dict = {}
    for line in fresh:
        key = measurement_key(line)
        if key not in fresh_best or line["gflops"] > fresh_best[key]:
            fresh_best[key] = line["gflops"]
    regressions = 0
    for key in sorted(fresh_best, key=fmt_key):
        new = fresh_best[key]
        if key not in base:
            log(f"NEW        {fmt_key(key)}: {new:.2f} GF/s "
                "(no history; report-only)")
            continue
        bl, n_hist = base[key]
        floor = (1.0 - tolerance) * bl
        if n_hist < min_history:
            log(f"THIN       {fmt_key(key)}: {new:.2f} vs baseline "
                f"{bl:.2f} GF/s ({n_hist} < {min_history} entries; "
                "report-only)")
            continue
        if new < floor:
            regressions += 1
            log(f"REGRESSION {fmt_key(key)}: {new:.2f} < {floor:.2f} GF/s "
                f"(baseline {bl:.2f} = median of best {best_k} over "
                f"{n_hist} entries, tolerance {tolerance:.0%})")
        else:
            log(f"OK         {fmt_key(key)}: {new:.2f} >= {floor:.2f} GF/s "
                f"(baseline {bl:.2f}, {n_hist} entries)")
    # serve-speedup floor: judge the BEST fresh speedup per key
    best_speedup = _best_speedup_per_key(fresh, "serve")
    for key in sorted(best_speedup, key=fmt_key):
        s = best_speedup[key]
        if s < min_serve_speedup:
            regressions += 1
            log(f"REGRESSION {fmt_key(key)}: batched-vs-singles speedup "
                f"{s:.2f}x < {min_serve_speedup:.1f}x (ISSUE-11 serving "
                "floor; history-free leg)")
        else:
            log(f"OK         {fmt_key(key)}: batched-vs-singles speedup "
                f"{s:.2f}x >= {min_serve_speedup:.1f}x")
    # autotune-speedup floor (ISSUE 15, docs/autotune.md): the learned
    # route table vs the pinned worst-case route (s=8 + native trsm) —
    # history-free like the serve leg, so a first-round autotune
    # measurement already gates
    for key, s in sorted(_best_speedup_per_key(fresh, "autotune").items(),
                         key=lambda kv: fmt_key(kv[0])):
        if s < min_autotune_speedup:
            regressions += 1
            log(f"REGRESSION {fmt_key(key)}: learned-vs-pinned-worst "
                f"speedup {s:.2f}x < {min_autotune_speedup:.2f}x "
                "(ISSUE-15 autotune floor; history-free leg)")
        else:
            log(f"OK         {fmt_key(key)}: learned-vs-pinned-worst "
                f"speedup {s:.2f}x >= {min_autotune_speedup:.2f}x")
    # fleet-scaling floor (ISSUE 18, docs/fleet.md): N replicas vs one
    # through the same router — history-free like the serve/autotune
    # legs, so a first-round fleet measurement already gates
    for key, s in sorted(_best_speedup_per_key(fresh, "fleet").items(),
                         key=lambda kv: fmt_key(kv[0])):
        if s < min_fleet_scaling:
            regressions += 1
            log(f"REGRESSION {fmt_key(key)}: fleet N-vs-1 scaling "
                f"{s:.2f}x < {min_fleet_scaling:.2f}x "
                "(ISSUE-18 fleet floor; history-free leg)")
        else:
            log(f"OK         {fmt_key(key)}: fleet N-vs-1 scaling "
                f"{s:.2f}x >= {min_fleet_scaling:.2f}x")
    # fused-step A/B completeness (ISSUE 19, docs/pallas_panel.md
    # "Fused step kernel"): the fstep workload is a PAIRED claim — a
    # fused-step measurement without its pinned composed-chain partner
    # (or vice versa) cannot support the step-gap story, so the gate
    # fails the half-pair loudly. History-free like the floors above.
    fstep_variants = {line.get("variant") for line in fresh
                      if line.get("workload") == "fstep"}
    if fstep_variants:
        missing = {"fstep", "fstep+fs1"} - fstep_variants
        if missing:
            regressions += 1
            log(f"REGRESSION fstep A/B pair incomplete: missing "
                f"{sorted(missing)} (ISSUE-19 fused-step leg; "
                "history-free)")
        else:
            log(f"OK         fstep A/B pair complete "
                f"({sorted(fstep_variants)})")
    return regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="bench-regression gate (see module docstring)")
    ap.add_argument("--history", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".bench_history.jsonl"))
    ap.add_argument("--fresh", nargs="*", default=[],
                    help="obs artifacts (bench_result records) or bare "
                         "measurement-line files with the fresh numbers")
    ap.add_argument("--replay", action="store_true",
                    help="replay the history's own best entry per key as "
                         "the fresh measurement (hermetic CI mode)")
    ap.add_argument("--tolerance", type=float, default=0.10)
    ap.add_argument("--min-history", type=int, default=3)
    ap.add_argument("--best-k", type=int, default=3)
    ap.add_argument("--inject-slowdown", type=float, default=0.0,
                    metavar="F",
                    help="scale every fresh measurement by (1 - F): the "
                         "synthetic-regression drill (CI runs F=0.2 and "
                         "requires a nonzero exit)")
    ap.add_argument("--min-serve-speedup", type=float,
                    default=DEFAULT_MIN_SERVE_SPEEDUP,
                    help="history-free floor on the serve arm's batched-"
                         "vs-singles speedup field (ISSUE 11: >= 3x)")
    ap.add_argument("--min-autotune-speedup", type=float,
                    default=DEFAULT_MIN_AUTOTUNE_SPEEDUP,
                    help="history-free floor on the autotune arm's "
                         "learned-table vs pinned-worst-case-route "
                         "speedup field (ISSUE 15; docs/autotune.md)")
    ap.add_argument("--min-fleet-scaling", type=float,
                    default=DEFAULT_MIN_FLEET_SCALING,
                    help="history-free floor on the fleet arm's "
                         "N-replica vs 1-replica requests/s ratio "
                         "(ISSUE 18; docs/fleet.md)")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0
    if not args.replay and not args.fresh:
        print("bench_gate: need --fresh artifacts or --replay",
              file=sys.stderr)
        return 2
    if not 0.0 <= args.tolerance < 1.0 or not 0.0 <= args.inject_slowdown < 1.0:
        print("bench_gate: tolerance/inject-slowdown must be in [0, 1)",
              file=sys.stderr)
        return 2

    try:
        history = read_history_records(args.history)
    except (OSError, ValueError) as e:
        print(f"bench_gate: {e}", file=sys.stderr)
        return 1
    if args.replay:
        best_per_key: dict = {}
        for line in history:
            key = measurement_key(line)
            if key not in best_per_key \
                    or line["gflops"] > best_per_key[key]["gflops"]:
                best_per_key[key] = line
        fresh = list(best_per_key.values())
        mode = "replay"
    else:
        try:
            fresh = load_fresh(args.fresh)
        except (OSError, ValueError) as e:
            print(f"bench_gate: {e}", file=sys.stderr)
            return 1
        mode = f"fresh x{len(args.fresh)}"
    if not fresh:
        print("bench_gate: no fresh measurements found", file=sys.stderr)
        return 1
    if args.inject_slowdown:
        fresh = [dict(line, gflops=line["gflops"]
                      * (1.0 - args.inject_slowdown)) for line in fresh]
        mode += f" +{args.inject_slowdown:.0%} injected slowdown"

    print(f"bench_gate: {mode}, {len(history)} history entries, "
          f"{len(fresh)} fresh measurements "
          f"(tolerance {args.tolerance:.0%}, min-history "
          f"{args.min_history}, best-k {args.best_k})")
    regressions = run_gate(history, fresh, tolerance=args.tolerance,
                           min_history=args.min_history,
                           best_k=args.best_k,
                           min_serve_speedup=args.min_serve_speedup,
                           min_autotune_speedup=args.min_autotune_speedup,
                           min_fleet_scaling=args.min_fleet_scaling)
    if regressions:
        print(f"bench_gate: {regressions} regressed key(s)",
              file=sys.stderr)
        # the per-step attribution is already in the fresh artifact
        # (ISSUE 16 critpath records): name the dominant step category
        # in the verdict itself, so the trip says WHERE before anyone
        # runs the explainer
        step = worst_step_category(args.fresh or [])
        if step is not None:
            print(f"bench_gate: dominant step category in fresh "
                  f"artifact: {step}", file=sys.stderr)
        # the explainer is one command away (ISSUE 14): diff the fresh
        # obs artifact against a known-good merged artifact — per-phase
        # device walls, compile seconds, retraces, comm bytes, overlap
        # fractions, accuracy — and the ranked report names the phase;
        # --json adds the per-step category deltas machine-readably
        fresh_art = args.fresh[0] if args.fresh else "<fresh.jsonl>"
        print("bench_gate: diagnose with: python scripts/perf_diff.py "
              f"<baseline_merged.jsonl> {fresh_art} [--json]",
              file=sys.stderr)
        return 1
    print("bench_gate: no regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
