#!/usr/bin/env python
"""Perf-regression explainer: diff two merged obs artifacts (ISSUE 14).

    python scripts/perf_diff.py BASELINE.jsonl FRESH.jsonl \\
        [--threshold 0.10] [--top N] [--inject-slowdown PHASE=F | F]

``scripts/bench_gate.py`` says *that* a key regressed; this tool says
*where*. Both inputs are merged ``DLAF_METRICS_PATH`` artifacts
(``obs.aggregate -o``), ideally enriched with the device-timeline
records (``python -m dlaf_tpu.obs.devtrace ... -o``). Per artifact it
extracts:

* **per-phase device wall** — ``devtrace`` records' per-phase ``wall_s``
  (the measured device busy union, not host wall);
* **host span wall** per span name (``dur_s`` sums — the coarse view
  when no devtrace records ride along);
* **compile seconds** per site (``program`` compile records);
* **retrace counts** per site (``program`` retrace records +
  ``dlaf_retrace_total`` counters, last snapshot);
* **comm bytes** per (kind, axis)
  (``dlaf_comm_collective_bytes_total``, last snapshot per rank,
  summed);
* **measured overlap fraction** per (algo, axis) (``measured_overlap``
  records, collective-time-weighted mean);
* **worst accuracy bound_ratio** (``accuracy`` records);
* **per-step category walls** — ``critpath`` records' per-step
  panel/bulk/exposed-comm/copy walls plus the step-boundary gap (keyed
  at the boundary it precedes: the gap after step k is
  ``<algo>.step<k+1> gap``), so a regression names not just the phase
  but the STEP and CATEGORY that moved (ISSUE 16).

The report is RANKED what-changed: every change sorted by severity
(relative change weighted by absolute magnitude), worst first; changes
in the bad direction beyond ``--threshold`` are REGRESSION lines naming
the phase/site/key. ``--inject-slowdown cholesky=0.5`` scales the FRESH
artifact's matching device-phase walls (and its host span walls) by
1.5x before diffing — the CI must-trip drill: the injected phase must
top the ranking and exit 1. ``--inject-slowdown`` specs matching a
step-category label (``cholesky.step002 gap=0.5`` or a bare
``cholesky.step002``-prefixed label) scale the matching step categories
instead, so the step-level drill trips the step-level finding.

``--json`` prints the full machine-readable report to stdout instead of
the human ranking: ``{"findings": [...], "regressions": [...],
"worst_step": {...}}`` where each finding carries
kind/label/old/new/delta/rel/severity/regression and ``worst_step`` is
the most severe step-category finding that got worse
(``scripts/bench_gate.py`` splices it into its verdict).

Exit status: 0 = no regression beyond threshold; 1 = >= 1 regression
(each named); 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dlaf_tpu.obs.sinks import read_records


def _finite(x) -> bool:
    return isinstance(x, (int, float)) and not isinstance(x, bool) \
        and math.isfinite(x)


def extract(records) -> dict:
    """The comparable facts of one merged artifact (module docstring)."""
    facts = {
        "phase_wall": {},       # phase -> device wall s (devtrace)
        "host_wall": {},        # span name -> sum dur_s
        "compile_s": {},        # site -> sum compile s
        "retraces": {},         # site -> count
        "comm_bytes": {},       # (kind, axis) -> bytes
        "overlap": {},          # (algo, axis) -> weighted overlap frac
        "worst_bound_ratio": None,
        "coverage": None,       # worst devtrace coverage
        "step_cat": {},         # "<algo>.stepNNN <cat>" -> seconds
    }
    overlap_acc: dict = {}
    last_snap: dict = {}
    for r in records:
        if not isinstance(r, dict):
            continue
        rtype = r.get("type")
        if rtype == "devtrace":
            for phase, cell in (r.get("phases") or {}).items():
                w = cell.get("wall_s")
                if _finite(w):
                    facts["phase_wall"][phase] = \
                        facts["phase_wall"].get(phase, 0.0) + w
            cov = r.get("coverage")
            if _finite(cov):
                facts["coverage"] = cov if facts["coverage"] is None \
                    else min(facts["coverage"], cov)
        elif rtype == "measured_overlap":
            key = (r.get("algo", "?"), r.get("axis", "?"))
            if _finite(r.get("overlap_frac")) \
                    and _finite(r.get("collective_s")):
                acc = overlap_acc.setdefault(key, [0.0, 0.0])
                acc[0] += r["overlap_frac"] * r["collective_s"]
                acc[1] += r["collective_s"]
        elif rtype == "span":
            if _finite(r.get("dur_s")):
                name = r.get("name", "?")
                facts["host_wall"][name] = \
                    facts["host_wall"].get(name, 0.0) + r["dur_s"]
        elif rtype == "program":
            site = r.get("site", "?")
            if r.get("event") == "compile" and _finite(r.get("compile_s")):
                facts["compile_s"][site] = \
                    facts["compile_s"].get(site, 0.0) + r["compile_s"]
            elif r.get("event") == "retrace":
                facts["retraces"][site] = facts["retraces"].get(site, 0) + 1
        elif rtype == "critpath":
            algo = r.get("algo", "?")
            for s in r.get("steps") or []:
                if not isinstance(s, dict) or s.get("empty") \
                        or not isinstance(s.get("step"), int):
                    continue
                k = s["step"]
                for cat, key in (("panel", "panel_s"), ("bulk", "bulk_s"),
                                 ("comm", "comm_exposed_s"),
                                 ("copy", "copy_s")):
                    if _finite(s.get(key)):
                        lbl = f"{algo}.step{k:03d} {cat}"
                        facts["step_cat"][lbl] = \
                            facts["step_cat"].get(lbl, 0.0) + s[key]
                # the gap after step k stalls the NEXT step's start:
                # key it at the boundary it precedes
                if _finite(s.get("gap_after_s")):
                    lbl = f"{algo}.step{k + 1:03d} gap"
                    facts["step_cat"][lbl] = \
                        facts["step_cat"].get(lbl, 0.0) + s["gap_after_s"]
        elif rtype == "accuracy":
            br = r.get("bound_ratio")
            if r.get("nonfinite") is True:
                facts["worst_bound_ratio"] = float("inf")
            elif _finite(br):
                cur = facts["worst_bound_ratio"]
                if cur is None or br > cur:
                    facts["worst_bound_ratio"] = br
        elif rtype == "metrics":
            last_snap[r.get("rank", 0)] = r
    for key, (num, den) in overlap_acc.items():
        facts["overlap"][key] = num / den if den > 0 else 0.0
    retrace_counters: dict = {}
    for snap in last_snap.values():
        for m in snap.get("metrics") or []:
            if not isinstance(m, dict) or not _finite(m.get("value")):
                continue
            labels = m.get("labels") or {}
            if m.get("name") == "dlaf_comm_collective_bytes_total":
                key = (labels.get("kind", "?"), labels.get("axis", "?"))
                facts["comm_bytes"][key] = \
                    facts["comm_bytes"].get(key, 0.0) + m["value"]
            elif m.get("name") == "dlaf_retrace_total":
                site = labels.get("site", "?")
                retrace_counters[site] = retrace_counters.get(site, 0.0) \
                    + m["value"]
    for site, v in retrace_counters.items():
        # the counter's first trace = 1; keep whichever evidence is
        # larger so record-trail and counter-trail artifacts compare
        facts["retraces"][site] = max(facts["retraces"].get(site, 0),
                                      int(v))
    return facts


def _rel(old: float, new: float) -> float:
    if old == 0.0:
        return math.inf if new > 0 else 0.0
    return (new - old) / abs(old)


def diff(a: dict, b: dict, threshold: float) -> list:
    """Ranked findings (dicts with severity/regression/worse/kind/label/
    old/new/delta/rel/line keys), worst first. Direction conventions:
    walls/compile/retraces/bytes/bound_ratio UP is bad; overlap fraction
    DOWN is bad."""
    findings = []

    def add(kind, label, old, new, *, unit="ms", scale=1e3, bad_up=True,
            fmt="{:.2f}", min_abs=0.0):
        if old is None and new is None:
            return
        if old is None or new is None:
            # a metric family present on only ONE side is instrumentation
            # skew (a baseline predating the devtrace/accuracy records, a
            # newly named span), not a measured perf change: report it
            # informationally, never as a REGRESSION — the exit-code
            # contract must not trip on a better-instrumented fresh run
            side = "only in fresh" if old is None else "only in baseline"
            v = float(new if old is None else old)
            findings.append({
                "severity": 0.0, "regression": False, "worse": False,
                "kind": kind, "label": label,
                "old": old, "new": new, "delta": None, "rel": None,
                "line": (f"{kind:<14s} {label}: " + fmt.format(v * scale)
                         + f" {unit} ({side}; not comparable)")})
            return
        old_v, new_v = float(old), float(new)
        delta = new_v - old_v
        if abs(delta) * scale < min_abs:
            return
        rel = _rel(old_v, new_v)
        worse = delta > 0 if bad_up else delta < 0
        is_reg = worse and (abs(rel) > threshold or math.isinf(rel))
        # severity: relative change, damped by absolute size so a
        # 0.01 ms phase tripling never outranks a 100 ms phase +30%
        sev = min(abs(rel), 10.0) * abs(delta) * scale
        arrow = "+" if delta >= 0 else ""
        rel_s = "new" if math.isinf(rel) else f"{arrow}{rel * 100:.1f}%"
        findings.append({
            "severity": sev, "regression": is_reg, "worse": worse,
            "kind": kind, "label": label,
            "old": old_v, "new": new_v, "delta": delta,
            "rel": None if math.isinf(rel) else rel,
            "line": (f"{kind:<14s} {label}: "
                     + fmt.format(old_v * scale) + " -> "
                     + fmt.format(new_v * scale) + f" {unit} ({rel_s})")})

    for phase in sorted(set(a["phase_wall"]) | set(b["phase_wall"])):
        add("device-phase", phase, a["phase_wall"].get(phase),
            b["phase_wall"].get(phase), min_abs=0.01)
    for name in sorted(set(a["host_wall"]) | set(b["host_wall"])):
        add("host-span", name, a["host_wall"].get(name),
            b["host_wall"].get(name), min_abs=0.01)
    for site in sorted(set(a["compile_s"]) | set(b["compile_s"])):
        add("compile", site, a["compile_s"].get(site),
            b["compile_s"].get(site), unit="s", scale=1.0,
            min_abs=0.01)
    for site in sorted(set(a["retraces"]) | set(b["retraces"])):
        add("retraces", site, a["retraces"].get(site),
            b["retraces"].get(site), unit="traces", scale=1.0,
            fmt="{:.0f}")
    for key in sorted(set(a["comm_bytes"]) | set(b["comm_bytes"])):
        add("comm-bytes", f"{key[0]}/{key[1]}", a["comm_bytes"].get(key),
            b["comm_bytes"].get(key), unit="MiB", scale=1.0 / 2**20,
            min_abs=0.01)
    for key in sorted(set(a["overlap"]) | set(b["overlap"])):
        add("overlap-frac", f"{key[0]}/{key[1]}", a["overlap"].get(key),
            b["overlap"].get(key), unit="%", scale=100.0, bad_up=False,
            fmt="{:.1f}")
    for lbl in sorted(set(a["step_cat"]) | set(b["step_cat"])):
        add("step-category", lbl, a["step_cat"].get(lbl),
            b["step_cat"].get(lbl), min_abs=0.01)
    add("bound-ratio", "worst accuracy", a["worst_bound_ratio"],
        b["worst_bound_ratio"], unit="", scale=1.0, fmt="{:.3g}")
    findings.sort(key=lambda f: -f["severity"])
    return findings


def worst_step(findings):
    """The most severe step-category finding that got worse, or None —
    the per-step verdict line ``bench_gate`` splices in."""
    for f in findings:
        if f["kind"] == "step-category" and f["worse"]:
            return f
    return None


def parse_inject(spec: str):
    """``PHASE=FACTOR`` or bare ``FACTOR`` -> (phase or None, factor)."""
    if "=" in spec:
        phase, _, factor = spec.partition("=")
        return phase, float(factor)
    return None, float(spec)


def inject_slowdown(facts: dict, phase, factor: float) -> None:
    """Scale the fresh artifact's device-phase walls (and host span
    walls, so artifacts without devtrace records still drill) by
    ``1 + factor`` — matching ``phase`` only, or every phase when
    None. A spec naming a step-category label (exactly, or as a
    ``<algo>.stepNNN`` prefix) scales the matching step categories
    instead — the step-level must-trip drill."""
    step_hits = [lbl for lbl in facts["step_cat"]
                 if phase is not None
                 and (lbl == phase or lbl.startswith(phase + " "))]
    if step_hits:
        for lbl in step_hits:
            facts["step_cat"][lbl] *= 1.0 + factor
        return
    for table in ("phase_wall", "host_wall"):
        for name in facts[table]:
            if phase is None or name == phase:
                facts[table][name] *= 1.0 + factor
    if phase is None:
        for lbl in facts["step_cat"]:
            facts["step_cat"][lbl] *= 1.0 + factor


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="perf-regression explainer (see module docstring)")
    ap.add_argument("baseline")
    ap.add_argument("fresh")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative-change threshold for a REGRESSION "
                         "verdict (default 0.10)")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--inject-slowdown", default="", metavar="PHASE=F",
                    help="scale the fresh artifact's matching phase "
                         "walls by 1+F before diffing (the CI "
                         "must-trip drill)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable report to stdout "
                         "instead of the human ranking (same exit "
                         "codes)")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0
    if not 0.0 <= args.threshold < 10.0 or args.top < 1:
        print("perf_diff: bad --threshold/--top", file=sys.stderr)
        return 2
    try:
        a = extract(read_records(args.baseline))
        b = extract(read_records(args.fresh))
    except (OSError, ValueError) as e:
        print(f"perf_diff: {e}", file=sys.stderr)
        return 1
    if not (a["phase_wall"] or a["host_wall"] or a["step_cat"]) \
            or not (b["phase_wall"] or b["host_wall"] or b["step_cat"]):
        print("perf_diff: an artifact carries neither devtrace phases, "
              "span records, nor critpath steps — nothing to attribute",
              file=sys.stderr)
        return 1
    mode = ""
    if args.inject_slowdown:
        try:
            phase, factor = parse_inject(args.inject_slowdown)
        except ValueError:
            print(f"perf_diff: bad --inject-slowdown "
                  f"{args.inject_slowdown!r}", file=sys.stderr)
            return 2
        inject_slowdown(b, phase, factor)
        mode = (f" [+{factor:.0%} injected slowdown on "
                f"{phase or 'every phase'}]")
    findings = diff(a, b, args.threshold)
    regressions = [f["line"] for f in findings if f["regression"]]
    ws = worst_step(findings)
    if args.json:
        print(json.dumps({
            "baseline": args.baseline, "fresh": args.fresh,
            "threshold": args.threshold,
            "coverage": {"baseline": a["coverage"],
                         "fresh": b["coverage"]},
            "findings": findings,
            "regressions": regressions,
            "worst_step": ws,
        }, indent=1, sort_keys=True))
        return 1 if regressions else 0
    print(f"perf_diff: {args.baseline} -> {args.fresh}{mode}")
    if a["coverage"] is not None or b["coverage"] is not None:
        fmt = lambda c: "-" if c is None else f"{c * 100:.1f}%"  # noqa: E731
        print(f"  devtrace coverage: {fmt(a['coverage'])} -> "
              f"{fmt(b['coverage'])}")
    shown = 0
    for f in findings:
        verdict = "REGRESSION" if f["regression"] else \
            ("  worse   " if f["worse"] else "  ok      ")
        if shown < args.top or f["regression"]:
            print(f"  {verdict} {f['line']}")
            shown += 1
    if not findings:
        print("  (no measurable differences)")
    if regressions:
        print(f"perf_diff: {len(regressions)} regression(s); worst: "
              f"{regressions[0]}", file=sys.stderr)
        if ws is not None:
            print(f"perf_diff: worst step category: {ws['line'].strip()}",
                  file=sys.stderr)
        return 1
    print("perf_diff: no regression beyond "
          f"{args.threshold:.0%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
