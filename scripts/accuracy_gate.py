#!/usr/bin/env python
"""CI accuracy-regression gate over the append-only accuracy history.

    python scripts/accuracy_gate.py --fresh obs_artifact.jsonl [...]
    python scripts/accuracy_gate.py --replay                  # hermetic CI
    python scripts/accuracy_gate.py --inject corrupt_collective  # drill

The accuracy counterpart of ``scripts/bench_gate.py`` (ISSUE 8,
docs/accuracy.md): fresh ``accuracy`` records (``dlaf_tpu.obs.accuracy``
— the ``DLAF_ACCURACY`` knob's artifact trail) are gated per key
``(site, metric, platform, n, nb, dtype)`` on TWO legs:

* **analytic budget** — the record's ``bound_ratio = value /
  (c * n * eps_eff)`` must stay below ``--budget`` (default 1.0: the
  residual may not exceed its c*n*eps backward-error budget, with
  ``eps_eff`` the platform-honest epsilon of
  ``miniapp/checks.effective_eps``). This leg needs NO history — it
  gates every key, including brand-new ones;
* **history drift** — the fresh worst ratio must stay below ``--drift``
  (default 4.0) times the median historical ratio of the same key from
  the git-tracked ``.accuracy_history.jsonl``. Keys with fewer than
  ``--min-history`` (default 3) entries are drift-report-only (a new
  site needs a few rounds of history before drift can gate it; the
  budget leg still applies).

A **non-finite** fresh estimate (``nonfinite: true`` records — NaN/Inf
residuals, the signature of real corruption) is an automatic regression
on any key.

Fresh measurements come from ``--fresh`` files — obs JSONL artifacts
whose ``accuracy`` records carry the estimates, or bare accuracy-history
line files. ``--replay`` instead replays each history key's median entry
as the fresh measurement (hermetic: clean committed history must exit
0). ``--inject nan_tile|corrupt_collective`` runs the built-in
corruption drill: a tiny Cholesky is factored with the named
``dlaf_tpu.health.inject`` fault armed, probed with the shared device
estimator, and the resulting records are gated — the drill MUST exit
nonzero, proving the gate trips on real corruption, not only on
synthetic numbers (``ci/run.sh smoke`` asserts exactly that).

``--record-fresh`` appends the passing fresh lines (stamped ts/source)
to the history — how a key accumulates the entries the drift leg needs.

Both gates share ONE validating history reader
(``dlaf_tpu.obs.sinks.read_history_records``, parameterized by kind):
a malformed or non-finite history line fails the gate loudly instead of
skewing a baseline.

Exit status: 0 = no regression; 1 = regression (or invalid history /
no usable fresh measurements); 2 = usage error.
"""

from __future__ import annotations

import argparse
import math
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dlaf_tpu.obs.sinks import (accuracy_record_to_history_line,
                                append_history_line, read_history_records,
                                read_records, validate_history_line)

INJECT_MODES = ("nan_tile", "corrupt_collective")


def measurement_key(line: dict) -> tuple:
    """The baseline key: (site, metric, platform, n, nb, dtype)."""
    return (line.get("site"), line.get("metric"), line.get("platform"),
            line.get("n"), line.get("nb"), line.get("dtype"))


def fmt_key(key: tuple) -> str:
    site, metric, platform, n, nb, dtype = key
    return f"{site}/{metric} [{platform}] n={n} nb={nb} {dtype}"


def load_fresh(paths) -> list:
    """Measurement lines from ``--fresh`` files: ``accuracy`` records of
    obs artifacts (projected onto the history-line shape by the shared
    ``accuracy_record_to_history_line``), or bare accuracy-history
    lines. Invalid lines are rejected loudly; nonfinite records ride
    through as ``bound_ratio: inf`` so the gate can trip on them."""
    fresh = []
    for path in paths:
        for r in read_records(path):
            if not isinstance(r, dict):
                raise ValueError(f"{path}: non-object record")
            if r.get("type") == "accuracy":
                line = accuracy_record_to_history_line(r)
                if line is None:
                    continue        # informational metric (no budget)
            elif "bound_ratio" in r and "type" not in r:
                line = r            # bare history-style line
            else:
                continue            # spans/metrics/etc. ride along
            if not (isinstance(line.get("bound_ratio"), float)
                    and math.isinf(line["bound_ratio"])):
                # artifact records carry no ts/source (the sink stamps ts
                # on the envelope, not the payload); stamp placeholders so
                # the SHARED history validator checks the rest of the line
                probe = dict(line)
                probe.setdefault("ts", "fresh")
                probe.setdefault("source", "fresh")
                errors = validate_history_line(probe, kind="accuracy")
                if errors:
                    raise ValueError(f"{path}: invalid fresh accuracy "
                                     "measurement: " + "; ".join(errors))
            fresh.append(line)
    return fresh


def baselines(history) -> dict:
    """{key: (median bound_ratio, n_history)} — the plain median: an
    accuracy baseline must track the typical estimate, and neither one
    lucky low probe nor one noisy high one should move it."""
    per_key: dict = {}
    for line in history:
        per_key.setdefault(measurement_key(line), []).append(
            line["bound_ratio"])
    return {key: (statistics.median(vals), len(vals))
            for key, vals in per_key.items()}


def run_gate(history, fresh, *, budget: float, drift: float,
             min_history: int, log=print) -> int:
    """Gate fresh worst-per-key bound ratios; returns the number of
    regressed keys. Keys without fresh measurements are skipped (the
    gate judges what this run measured); thin-history keys are
    drift-report-only but still budget-gated."""
    base = baselines(history)
    fresh_worst: dict = {}
    for line in fresh:
        key = measurement_key(line)
        ratio = line.get("bound_ratio")
        if key not in fresh_worst or ratio > fresh_worst[key]:
            fresh_worst[key] = ratio
    regressions = 0
    for key in sorted(fresh_worst, key=fmt_key):
        worst = fresh_worst[key]
        if not math.isfinite(worst):
            regressions += 1
            log(f"REGRESSION {fmt_key(key)}: non-finite accuracy estimate "
                "(corrupted result)")
            continue
        if worst > budget:
            regressions += 1
            log(f"REGRESSION {fmt_key(key)}: bound_ratio {worst:.3g} > "
                f"analytic budget {budget:.3g} (residual exceeds its "
                "c*n*eps_eff backward-error bound)")
            continue
        if key not in base:
            log(f"NEW        {fmt_key(key)}: bound_ratio {worst:.3g} <= "
                f"budget {budget:.3g} (no history; drift leg report-only)")
            continue
        bl, n_hist = base[key]
        ceiling = drift * bl
        if n_hist < min_history:
            log(f"THIN       {fmt_key(key)}: bound_ratio {worst:.3g} vs "
                f"median {bl:.3g} ({n_hist} < {min_history} entries; drift "
                "leg report-only)")
            continue
        if worst > ceiling:
            regressions += 1
            log(f"REGRESSION {fmt_key(key)}: bound_ratio {worst:.3g} > "
                f"{ceiling:.3g} (drift {drift:g}x over median {bl:.3g} of "
                f"{n_hist} entries)")
        else:
            log(f"OK         {fmt_key(key)}: bound_ratio {worst:.3g} <= "
                f"min(budget {budget:.3g}, drift ceiling {ceiling:.3g}) "
                f"({n_hist} entries)")
    return regressions


def run_inject_drill(kind: str, log=print) -> list:
    """The corruption drill: factor a tiny HPD matrix with the named
    ``health.inject`` fault armed and return the fresh accuracy lines of
    the probed (corrupted) factor. ``corrupt_collective`` poisons the
    nth traced diagonal broadcast of a 2x2-grid distributed Cholesky;
    ``nan_tile`` poisons one element of a locally factored L. Runs on
    whatever backend is up (CI pins JAX_PLATFORMS=cpu with 4 virtual
    devices)."""
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=4").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import dlaf_tpu.config as config
    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
    from dlaf_tpu.health import inject
    from dlaf_tpu.matrix.matrix import Matrix
    from dlaf_tpu.miniapp.generators import hpd_element_fn
    from dlaf_tpu.obs import accuracy

    config.initialize()
    n, nb = 64, 16
    size, block = GlobalElementSize(n, n), TileElementSize(nb, nb)
    if kind == "corrupt_collective":
        from dlaf_tpu.comm.grid import Grid

        mat = Matrix.from_element_fn(hpd_element_fn(n, np.float64), size,
                                     block, grid=Grid(2, 2))
        with inject.corrupt_collective("bcast"):
            fac = cholesky("L", mat)
    else:
        mat = Matrix.from_element_fn(hpd_element_fn(n, np.float64), size,
                                     block)
        # pin the poison into the referenced (strict lower) triangle: a
        # seed-drawn element could land above the diagonal, where the
        # uplo="L" probe's tril mask would zero it and the must-trip
        # drill would silently pass
        fac = inject.nan_tile(cholesky("L", mat), tile=(2, 1),
                              element=(3, 3))
    value = accuracy.cholesky_residual("L", mat, fac)
    res = accuracy.emit("accuracy_gate.drill", "cholesky_residual", value,
                        n=n, nb=nb, c=60.0, dtype=np.float64,
                        of=fac.storage, attrs={"inject": kind},
                        record=False)
    ratio = res.bound_ratio if res.finite else float("inf")
    log(f"accuracy_gate: drill [{kind}] probed residual "
        f"{value!r} -> bound_ratio {ratio!r}")
    return [{"site": res.site, "metric": res.metric,
             "platform": accuracy._platform_of(fac.storage),
             "dtype": "float64", "n": n, "nb": nb,
             "value": value if res.finite else float("inf"),
             "bound_ratio": ratio}]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="accuracy-regression gate (see module docstring)")
    ap.add_argument("--history", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".accuracy_history.jsonl"))
    ap.add_argument("--fresh", nargs="*", default=[],
                    help="obs artifacts (accuracy records) or bare "
                         "accuracy-history line files")
    ap.add_argument("--replay", action="store_true",
                    help="replay each history key's median entry as the "
                         "fresh measurement (hermetic CI mode)")
    ap.add_argument("--inject", choices=INJECT_MODES,
                    help="run the built-in corruption drill and gate its "
                         "records (CI requires a nonzero exit)")
    ap.add_argument("--budget", type=float, default=1.0,
                    help="analytic bound_ratio ceiling (history-free leg)")
    ap.add_argument("--drift", type=float, default=4.0,
                    help="allowed factor over the median historical ratio")
    ap.add_argument("--min-history", type=int, default=3)
    ap.add_argument("--record-fresh", action="store_true",
                    help="append passing fresh lines (stamped ts/source) "
                         "to the history log")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0
    modes = sum([bool(args.fresh), args.replay, args.inject is not None])
    if modes != 1:
        print("accuracy_gate: need exactly one of --fresh / --replay / "
              "--inject", file=sys.stderr)
        return 2
    if args.budget <= 0 or args.drift < 1.0:
        print("accuracy_gate: budget must be > 0 and drift >= 1",
              file=sys.stderr)
        return 2

    if os.path.exists(args.history):
        try:
            history = read_history_records(args.history, kind="accuracy")
        except (OSError, ValueError) as e:
            print(f"accuracy_gate: {e}", file=sys.stderr)
            return 1
    else:
        history = []        # budget leg still gates; drift is report-only
    if args.replay:
        per_key: dict = {}
        for line in history:
            per_key.setdefault(measurement_key(line), []).append(line)
        fresh = []
        for lines in per_key.values():
            lines.sort(key=lambda ln: ln["bound_ratio"])
            fresh.append(lines[len(lines) // 2])
        mode = "replay"
        if not history:
            print("accuracy_gate: --replay needs a history file",
                  file=sys.stderr)
            return 1
    elif args.inject:
        fresh = run_inject_drill(args.inject)
        mode = f"inject {args.inject}"
    else:
        try:
            fresh = load_fresh(args.fresh)
        except (OSError, ValueError) as e:
            print(f"accuracy_gate: {e}", file=sys.stderr)
            return 1
        mode = f"fresh x{len(args.fresh)}"
    if not fresh:
        print("accuracy_gate: no fresh accuracy measurements found",
              file=sys.stderr)
        return 1

    print(f"accuracy_gate: {mode}, {len(history)} history entries, "
          f"{len(fresh)} fresh measurements (budget {args.budget:g}, "
          f"drift {args.drift:g}x, min-history {args.min_history})")
    regressions = run_gate(history, fresh, budget=args.budget,
                           drift=args.drift, min_history=args.min_history)
    if regressions:
        print(f"accuracy_gate: {regressions} regressed key(s)",
              file=sys.stderr)
        return 1
    if args.record_fresh:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime())
        for line in fresh:
            append_history_line(args.history,
                                dict(line, ts=ts, source="accuracy_gate"),
                                kind="accuracy")
        print(f"accuracy_gate: recorded {len(fresh)} fresh line(s) to "
              f"{args.history}")
    print("accuracy_gate: no regressions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
