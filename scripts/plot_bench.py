#!/usr/bin/env python
"""Parse miniapp output lines and plot/tabulate scaling results.

TPU-native counterpart of the reference's ``scripts/plot_*.py``: consumes the
schema-stable ``[i] <t>s <gflops>GFlop/s ...`` lines from one or more run
logs and prints a per-configuration summary (median time, best GFLOP/s);
``--plot out.png`` additionally renders a matplotlib scaling curve when
matplotlib is available.
"""

import argparse
import re
import sys
from collections import defaultdict

LINE = re.compile(
    r"\[(\d+)\]\s+([0-9.eE+-]+)s\s+([0-9.eE+-]+)GFlop/s\s+(\S+)\s+\(([\d, ]+)\)"
    r"\s+\(([\d, ]+)\)\s+\(([\d, ]+)\)")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("logs", nargs="+", help="miniapp output files ('-' = stdin)")
    p.add_argument("--plot", default=None, help="write a PNG scaling plot")
    args = p.parse_args()
    groups = defaultdict(list)
    for path in args.logs:
        fh = sys.stdin if path == "-" else open(path)
        for line in fh:
            m = LINE.search(line)
            if not m:
                continue
            _, t, gf, kind, size, block, grid = m.groups()
            key = (kind, size.replace(" ", ""), block.replace(" ", ""),
                   grid.replace(" ", ""))
            groups[key].append((float(t), float(gf)))
    rows = []
    for key in sorted(groups):
        runs = groups[key]
        ts = sorted(t for t, _ in runs)
        med = ts[len(ts) // 2]
        best = max(g for _, g in runs)
        ndev = 1
        gr = key[3].strip("()").split(",")
        if len(gr) == 2:
            ndev = int(gr[0]) * int(gr[1])
        rows.append((key, med, best, ndev))
        print(f"{key[0]:>6} size={key[1]:>14} nb={key[2]:>10} grid={key[3]:>8} "
              f"runs={len(runs):>3} median={med:.4f}s best={best:.1f}GF/s "
              f"({best / ndev:.1f}/dev)")
    if args.plot and rows:
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.pyplot as plt

            xs = [r[3] for r in rows]
            ys = [r[2] for r in rows]
            plt.plot(xs, ys, "o-")
            plt.xlabel("devices")
            plt.ylabel("GFlop/s")
            plt.xscale("log", base=2)
            plt.yscale("log", base=2)
            plt.grid(True, which="both", alpha=0.3)
            plt.savefig(args.plot, dpi=120, bbox_inches="tight")
            print(f"wrote {args.plot}")
        except ImportError:
            print("matplotlib unavailable; table only", file=sys.stderr)


if __name__ == "__main__":
    main()
