#!/usr/bin/env python
"""Hardware knob sweep for the emulated-f64 fast path (round-2 perf push).

Measures, on the real accelerator with the fenced protocol
(``dlaf_tpu/common/sync.py``):

1. trailing-update microkernels at the N=4096 hot shape (m=3840, k=256):
   jnp ozaki syrk vs the fused Pallas predicated-square-grid syrk, matmul
   forms, and the slice-count knob (8 vs 7);
2. full miniapp_cholesky (N=4096 nb=256, BASELINE config #1) across the
   knob grid {ozaki_impl: jnp|pallas} x {f64_gemm_slices: 8|7};
3. the panel-latency chain: potrf_refined / tri_inv_refined /
   native emulated-f64 potrf / f32 potrf at nb=256;
4. an N-sweep (4096 / 8192 / 16384, run LAST — the 16384 point compiles a
   64-step unrolled program) of the winning configuration so amortization
   of the panel-latency chain is visible.

Stdout gets the full JSON results document re-printed after every
completed phase (consumers take the LAST line), so a wedge or wall-clock
kill mid-sweep still leaves everything already measured in the artifact;
a human table goes to stderr. Each phase is independently guarded.
"""

import json
import os
import sys

import numpy as np

# run as `python scripts/tpu_sweep.py`: sys.path[0] is scripts/, not the
# repo root — put the package dir on the path before any dlaf_tpu import
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from measure_common import append_history, best_time, log, peel  # noqa: E402
from measure_common import setup_env  # noqa: E402


def main():
    jax = setup_env()
    import jax.numpy as jnp

    import dlaf_tpu.config as config

    config.initialize()
    platform = jax.devices()[0].platform
    log(f"platform: {platform}, devices: {jax.devices()}")
    results = {"platform": platform, "micro": {}, "cholesky": {},
               "nsweep": {}, "panel": {}}

    # -- 1. trailing-update microkernels -----------------------------------
    try:
        from dlaf_tpu.tile_ops import ozaki as oz
        from dlaf_tpu.tile_ops.pallas_ozaki import (fused_slice_product,
                                                    fused_slice_syrk)

        m, k = 3840, 256
        rng = np.random.default_rng(0)
        a = jnp.asarray(rng.standard_normal((m, k)))
        b = jnp.asarray(rng.standard_normal((k, m)))
        flops_syrk = m * m * k          # lower-triangle-useful convention
        flops_mm = 2 * m * m * k

        for s in (8, 7):
            t = best_time(lambda x: oz.syrk_f64(x, slices=s), a)
            results["micro"][f"syrk_jnp_s{s}"] = {
                "t": t, "gflops": flops_syrk / t / 1e9}
            t = best_time(lambda x, y: oz.matmul_f64(x, y, slices=s), a, b)
            results["micro"][f"matmul_jnp_s{s}"] = {
                "t": t, "gflops": flops_mm / t / 1e9}

        # pallas fused kernels on pre-peeled slices (isolates kernel cost)
        # each pallas kernel timed under its own guard: a Mosaic
        # legalization failure in one form must not cost the others'
        # measurements (observed 2026-07-31: the scalar-prefetch syrk
        # failed AOT compile and took the whole phase down with it)
        for s in (8, 7):
            ia, _ = peel(a, s)
            ib, _ = peel(b.T, s)  # (s, m, k); product form wants (s,k,n)
            ibt = jnp.swapaxes(ib, -1, -2)
            try:
                t = best_time(lambda x: fused_slice_syrk(x), ia)
                results["micro"][f"syrk_pallas_s{s}"] = {
                    "t": t, "gflops": flops_syrk / t / 1e9}
            except Exception as e:
                log(f"micro syrk_pallas_s{s} failed: {e!r}"[:500])
            try:
                t = best_time(lambda x, y: fused_slice_product(x, y), ia, ibt)
                results["micro"][f"matmul_pallas_s{s}"] = {
                    "t": t, "gflops": flops_mm / t / 1e9}
            except Exception as e:
                log(f"micro matmul_pallas_s{s} failed: {e!r}"[:500])
        # end-to-end syrk through the config knob (peel + kernel + mirror)
        try:
            os.environ["DLAF_OZAKI_IMPL"] = "pallas"
            config.initialize()
            t = best_time(lambda x: oz.syrk_f64(x), a)
            results["micro"]["syrk_e2e_pallas_s8"] = {
                "t": t, "gflops": flops_syrk / t / 1e9}
        except Exception as e:
            log(f"micro syrk_e2e_pallas_s8 failed: {e!r}"[:500])
        finally:
            os.environ.pop("DLAF_OZAKI_IMPL", None)
            config.initialize()
    except Exception as e:
        log(f"micro phase failed: {e!r}")
    log(f"micro: {json.dumps(results['micro'], default=float)}")
    print(json.dumps(results, default=float), flush=True)

    # -- 2. full cholesky knob grid ----------------------------------------
    from dlaf_tpu.algorithms.cholesky import cholesky
    from dlaf_tpu.common.index2d import GlobalElementSize, TileElementSize
    from dlaf_tpu.matrix.matrix import Matrix
    from dlaf_tpu.miniapp.generators import hpd_element_fn
    from dlaf_tpu.types import total_ops

    def chol_time(n, nb, impl, slices):
        os.environ["DLAF_CHOLESKY_TRAILING"] = "ozaki"
        os.environ["DLAF_OZAKI_IMPL"] = impl
        os.environ["DLAF_F64_GEMM_SLICES"] = str(slices)
        config.initialize()
        try:
            ref = Matrix.from_element_fn(
                hpd_element_fn(n, np.float64), GlobalElementSize(n, n),
                TileElementSize(nb, nb), dtype=np.float64)

            def run(mat_storage):
                mat = ref.with_storage(mat_storage)
                out = cholesky("L", mat)
                return out.storage

            t = best_time(run, ref.storage + 0)
            return t, total_ops(np.float64, n**3 / 6, n**3 / 6) / t / 1e9
        finally:
            for k_ in ("DLAF_CHOLESKY_TRAILING", "DLAF_OZAKI_IMPL",
                       "DLAF_F64_GEMM_SLICES"):
                os.environ.pop(k_, None)
            config.initialize()

    n, nb = 4096, 256
    best_cfg, best_g = None, 0.0
    for impl in ("jnp", "pallas"):
        for s in (8, 7):
            key = f"impl={impl},slices={s}"
            try:
                t, g = chol_time(n, nb, impl, s)
                results["cholesky"][key] = {"t": t, "gflops": g}
                log(f"cholesky N={n} {key}: {t:.4f}s {g:.1f} GF/s")
                if platform == "tpu":
                    append_history(platform, n, nb, g, t,
                                   f"tpu_sweep knob grid {key}")
                if g > best_g:
                    best_g, best_cfg = g, (impl, s)
            except Exception as e:
                log(f"cholesky {key} failed: {e!r}")
    results["cholesky"]["best"] = (
        {"impl": best_cfg[0], "slices": best_cfg[1], "gflops": best_g}
        if best_cfg else None)
    print(json.dumps(results, default=float), flush=True)

    # -- 3. panel-latency chain --------------------------------------------
    try:
        from jax import lax

        from dlaf_tpu.tile_ops import mixed as mx

        nb_ = 256
        rng = np.random.default_rng(1)
        x = rng.standard_normal((nb_, nb_))
        spd = jnp.asarray(x @ x.T + nb_ * np.eye(nb_))
        l64 = jnp.linalg.cholesky(spd)

        f_refined = jax.jit(lambda m: mx.potrf_refined("L", m))
        f_fused = jax.jit(lambda m: mx.potrf_inv_refined("L", m))
        f_native = jax.jit(lambda m: jnp.tril(lax.linalg.cholesky(m)))
        f_f32 = jax.jit(
            lambda m: lax.linalg.cholesky(m.astype(jnp.float32)))
        f_inv = jax.jit(lambda m: mx.tri_inv_refined(m, lower=True))
        f_inv_native = jax.jit(lambda m: lax.linalg.triangular_solve(
            m, jnp.eye(nb_, dtype=m.dtype), left_side=True, lower=True))
        for name, fn, arg in [("potrf_refined", f_refined, spd),
                              # the op the mixed cholesky panel ACTUALLY
                              # runs per step (fused factor+inverse)
                              ("potrf_inv_refined", f_fused, spd),
                              ("potrf_native_f64", f_native, spd),
                              ("potrf_f32", f_f32, spd),
                              ("tri_inv_refined", f_inv, l64),
                              ("tri_inv_native", f_inv_native, l64)]:
            t = best_time(fn, arg)
            results["panel"][name] = {"t_ms": t * 1e3}
            log(f"panel {name}: {t*1e3:.3f} ms")
        # recursive trace-time seed (mixed_seed="recursive"): the latency
        # candidate of ROADMAP item 4 — time the fused op under each base
        for base in (32, 64, 128):
            os.environ["DLAF_MIXED_SEED"] = "recursive"
            os.environ["DLAF_MIXED_SEED_BASE"] = str(base)
            config.initialize()
            try:
                f_rec = jax.jit(lambda m: mx.potrf_inv_refined("L", m))
                t = best_time(f_rec, spd)
                results["panel"][f"potrf_inv_recursive_b{base}"] = {
                    "t_ms": t * 1e3}
                log(f"panel potrf_inv_recursive_b{base}: {t*1e3:.3f} ms")
            except Exception as e:
                log(f"panel recursive b{base} failed: {e!r}")
            finally:
                os.environ.pop("DLAF_MIXED_SEED", None)
                os.environ.pop("DLAF_MIXED_SEED_BASE", None)
                config.initialize()
    except Exception as e:
        log(f"panel phase failed: {e!r}")
    print(json.dumps(results, default=float), flush=True)

    # -- 4. N-sweep of the winner (LAST: the 16384 point compiles a
    # 64-step unrolled program and may eat the remaining wall-clock) ------
    if best_cfg:
        for nn in (4096, 8192, 16384):
            try:
                t, g = chol_time(nn, nb, *best_cfg)
                results["nsweep"][str(nn)] = {"t": t, "gflops": g}
                log(f"nsweep N={nn}: {t:.4f}s {g:.1f} GF/s")
                if platform == "tpu":
                    append_history(platform, nn, nb, g, t,
                                   "tpu_sweep N-sweep (best knobs)")
            except Exception as e:
                log(f"nsweep N={nn} failed: {e!r}")
            print(json.dumps(results, default=float), flush=True)


if __name__ == "__main__":
    main()
