#!/usr/bin/env python
"""North-star host-stage walls (VERDICT r3 item 8): the native bulge chase
at n=65536 and the D&C secular-threshold sweep, measured on the CPU
backend. Appends one JSON line per step to stdout as it lands (wedge-proof)
and aborts between steps if the TPU measurement session has started
(``.session4_auto`` appears) — host walls must not contend with silicon
numbers on this 1-core box.

Run:  python scripts/host_walls.py [--skip-chase] [--dnc-n 16384]
"""

import argparse
import json
import os
import resource
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def emit(rec):
    print(json.dumps(rec), flush=True)


def session_started():
    # a TPU measurement session owns the box. Two signals, either one
    # suffices: a live tpu_session*.sh process, or a session OUT dir
    # (.session4_auto, .session4b_live, .session4c_<ts>, ...) touched in
    # the last 4 h — prefix+mtime rather than an exact-name list so new
    # session scripts are covered without editing this guard, while
    # stale dirs from finished windows don't block host walls forever.
    # DLAF_HOST_WALLS_FORCE=1 bypasses the mtime-dir signal ONLY, for
    # runs deliberately chained to start the moment a session finishes
    # (its dirs are still mtime-fresh then); the live-process signal
    # stays active either way so a session firing mid-run still aborts
    # the remaining host walls.
    force = os.environ.get("DLAF_HOST_WALLS_FORCE", "").lower() \
        in ("1", "true", "yes")
    import subprocess
    try:
        # "bash .../tpu_sessionX.sh" = an EXECUTING session script; a bare
        # "SESSION=...tpu_session4d.sh bash tpu_watch.sh" watcher wrapper
        # (armed but idle) must not match
        if subprocess.run(["pgrep", "-f", r"bash [^ ]*tpu_session"],
                          stdout=subprocess.DEVNULL).returncode == 0:
            return True
    except OSError:
        pass
    if force:
        return False
    now = time.time()
    try:
        entries = os.listdir(REPO)
    except OSError:
        return False
    for e in entries:
        p = os.path.join(REPO, e)
        if e.startswith(".session") and os.path.isdir(p):
            try:
                if now - os.path.getmtime(p) < 4 * 3600:
                    return True
            except OSError:
                continue
    return False


def rss_gb():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-chase", action="store_true")
    ap.add_argument("--chase-n", type=int, default=65536)
    ap.add_argument("--band", type=int, default=128)
    ap.add_argument("--dnc-n", type=int, default=16384)
    ap.add_argument("--thresholds", default="2048,4096,8192")
    ap.add_argument("--dnc-big", type=int, default=0,
                    help="optional final single D&C run at this n")
    args = ap.parse_args()

    import numpy as np
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    import dlaf_tpu.config as config

    config.initialize()

    if not args.skip_chase and not session_started():
        from dlaf_tpu.eigensolver.band_to_tridiag import band_to_tridiag

        n, b = args.chase_n, args.band
        rng = np.random.default_rng(0)
        band = rng.standard_normal((b + 1, n))
        band[0] += 2 * b  # diagonally dominant, well-scaled
        log(f"chase n={n} b={b} (native, chase_threads=auto on "
            f"{os.cpu_count()} core(s))")
        t0 = time.perf_counter()
        res = band_to_tridiag(band, b)
        t = time.perf_counter() - t0
        emit({"step": "chase", "n": n, "b": b, "wall_s": round(t, 1),
              "rss_gb": round(rss_gb(), 1), "cores": os.cpu_count(),
              "d0": float(res.d[0])})
        log(f"chase: {t:.0f} s, rss {rss_gb():.1f} GB")

    from dlaf_tpu.eigensolver.tridiag_solver import tridiag_solver

    n = args.dnc_n
    rng = np.random.default_rng(1)
    d = rng.standard_normal(n)
    e = rng.standard_normal(n - 1)
    for thr in [int(x) for x in args.thresholds.split(",") if x]:
        if session_started():
            log("TPU session started; aborting remaining host walls")
            return
        os.environ["DLAF_SECULAR_DEVICE_MIN_K"] = str(thr)
        config.initialize()
        t0 = time.perf_counter()
        w, q = tridiag_solver(d, e, nb=512)
        w = np.asarray(w)
        t = time.perf_counter() - t0
        # sampled residual: a few columns of T q - w q
        cols = [0, n // 2, n - 1]
        qh = np.asarray(q[:, cols])
        tq = d[:, None] * qh
        tq[1:] += e[:, None] * qh[:-1]
        tq[:-1] += e[:, None] * qh[1:]
        resid = float(np.max(np.abs(tq - qh * w[cols][None, :])))
        emit({"step": "dnc", "n": n, "secular_device_min_k": thr,
              "wall_s": round(t, 1), "rss_gb": round(rss_gb(), 1),
              "sampled_resid": resid})
        log(f"dnc n={n} thr={thr}: {t:.0f} s, resid {resid:.1e}")
        del w, q, qh, tq

    if args.dnc_big and not session_started():
        os.environ.pop("DLAF_SECULAR_DEVICE_MIN_K", None)
        config.initialize()
        n = args.dnc_big
        d = rng.standard_normal(n)
        e = rng.standard_normal(n - 1)
        t0 = time.perf_counter()
        w, q = tridiag_solver(d, e, nb=512)
        np.asarray(w)
        t = time.perf_counter() - t0
        emit({"step": "dnc_big", "n": n, "wall_s": round(t, 1),
              "rss_gb": round(rss_gb(), 1)})
        log(f"dnc n={n}: {t:.0f} s")


if __name__ == "__main__":
    main()
