"""AOT memory probe for the single-chip HBM ceiling (config #1 N=16384).

Compiles the local cholesky factorization programs WITHOUT executing them
and prints ``compiled.memory_analysis()`` — the allocator's own accounting
of argument/output/temp/alias sizes — so OOM-vs-fit questions are answered
from the compile service instead of burning measurement-window minutes on
RESOURCE_EXHAUSTED runs (4b/4d/4f each lost an arm to one).

The probe A/Bs input donation (``cholesky(..., donate=True)``, the
reference's in-place semantics) against the pre-donation layout on the
scan trailing + scan accumulation form, the one whose straight-line
buffers are already bounded.

Usage:  python scripts/tpu_mem_probe.py [-n 16384] [--nb 256] [--unrolled]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fmt(analysis) -> str:
    gb = 1024 ** 3
    fields = ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes")
    parts = []
    for f in fields:
        v = getattr(analysis, f, None)
        if v is not None:
            parts.append(f"{f.replace('_size_in_bytes', '')}={v / gb:.2f}G")
    return " ".join(parts) or repr(analysis)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("-n", type=int, default=16384)
    p.add_argument("--nb", type=int, default=256)
    p.add_argument("--unrolled", action="store_true",
                   help="also compile the unrolled ozaki form (pays the "
                        "~19 s/step AOT constant unless the persistent "
                        "compilation cache has it)")
    args = p.parse_args()

    os.environ.setdefault("DLAF_COMPILATION_CACHE_DIR",
                          os.path.join(os.getcwd(), ".jax_cache"))
    import jax
    import jax.numpy as jnp

    from dlaf_tpu import config
    config.initialize(argv=[])
    import importlib

    # the algorithms package re-exports the cholesky FUNCTION under the
    # module's name; go through sys.modules for the module itself
    C = importlib.import_module("dlaf_tpu.algorithms.cholesky")

    n, nb = args.n, args.nb
    spec = jax.ShapeDtypeStruct((n, n), jnp.float64)
    hbm = 15.75  # v5e per-chip budget, GB

    def probe(name, jitted, *a, **kw):
        try:
            comp = jitted.lower(*a, **kw).compile()
        except Exception as e:  # report, keep probing the other arms
            print(f"{name}: COMPILE FAILED: {type(e).__name__}: "
                  f"{str(e)[:300]}")
            return
        m = comp.memory_analysis()
        gb = 1024 ** 3
        tot = sum(getattr(m, f, 0) or 0
                  for f in ("argument_size_in_bytes", "output_size_in_bytes",
                            "temp_size_in_bytes"))
        alias = getattr(m, "alias_size_in_bytes", 0) or 0
        print(f"{name}: {fmt(m)}  est_live={(tot - alias) / gb:.2f}G "
              f"(budget {hbm}G)", flush=True)

    # the donated jit IS _cholesky_local_scan since the donation lever;
    # the undonated control is a fresh jit of the same traced fn
    probe(f"scan+scanaccum n={n} DONATED", C._cholesky_local_scan,
          spec, uplo="L", nb=nb, use_mxu=True, use_mixed=True)
    undonated = jax.jit(
        C._cholesky_local_scan.__wrapped__,
        static_argnames=("uplo", "nb", "use_mxu", "use_mixed"))
    probe(f"scan+scanaccum n={n} undonated", undonated,
          spec, uplo="L", nb=nb, use_mxu=True, use_mixed=True)

    if args.unrolled:
        probe(f"unrolled-ozaki n={n} DONATED", C._cholesky_local,
              spec, uplo="L", nb=nb, trailing="ozaki")


if __name__ == "__main__":
    main()
