"""AOT memory probe for the single-chip HBM ceiling (config #1 N=16384).

Compiles the local cholesky factorization programs WITHOUT executing them
and prints ``compiled.memory_analysis()`` — the allocator's own accounting
of argument/output/temp/alias sizes — so OOM-vs-fit questions are answered
from the compile service instead of burning measurement-window minutes on
RESOURCE_EXHAUSTED runs (4b/4d/4f each lost an arm to one).

The probe A/Bs input donation (``cholesky(..., donate=True)``, the
reference's in-place semantics) against the pre-donation layout on the
scan trailing + scan accumulation form, the one whose straight-line
buffers are already bounded.

Usage:  python scripts/tpu_mem_probe.py [-n 16384] [--nb 256] [--unrolled]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


#: printed label -> dlaf_tpu.obs.telemetry memory_analysis_dict key (the
#: stable CLI output shape predates the telemetry API)
_FIELDS = (("argument", "args"), ("output", "output"), ("temp", "temp"),
           ("alias", "alias"), ("generated_code", "code"))


def fmt(memory: dict) -> str:
    gb = 1024 ** 3
    parts = [f"{label}={memory[key] / gb:.2f}G"
             for label, key in _FIELDS if key in memory]
    return " ".join(parts) or repr(memory)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("-n", type=int, default=16384)
    p.add_argument("--nb", type=int, default=256)
    p.add_argument("--unrolled", action="store_true",
                   help="also compile the unrolled ozaki form (pays the "
                        "~19 s/step AOT constant unless the persistent "
                        "compilation cache has it)")
    args = p.parse_args()

    os.environ.setdefault("DLAF_COMPILATION_CACHE_DIR",
                          os.path.join(os.getcwd(), ".jax_cache"))
    import jax
    import jax.numpy as jnp

    from dlaf_tpu import config
    config.initialize(argv=[])
    import importlib

    # the algorithms package re-exports the cholesky FUNCTION under the
    # module's name; go through sys.modules for the module itself
    C = importlib.import_module("dlaf_tpu.algorithms.cholesky")

    n, nb = args.n, args.nb
    spec = jax.ShapeDtypeStruct((n, n), jnp.float64)
    hbm = 15.75  # v5e per-chip budget, GB

    # the library now owns the AOT lower/compile + memory_analysis
    # plumbing this script used to hand-roll (ISSUE 7 satellite); the
    # probe rides it — and with DLAF_PROGRAM_TELEMETRY=1 the numbers
    # also land in the DLAF_METRICS_PATH artifact as program records
    from dlaf_tpu.obs import telemetry

    def probe(name, jitted, *a, **kw):
        site = "tpu_mem_probe." + name.split()[0]
        try:
            prog = telemetry.aot_compile(site, jitted, *a, **kw)
        except Exception as e:  # report, keep probing the other arms
            print(f"{name}: COMPILE FAILED: {type(e).__name__}: "
                  f"{str(e)[:300]}")
            return
        mem = prog.memory or {}
        gb = 1024 ** 3
        print(f"{name}: {fmt(mem)}  est_live={mem.get('peak', 0) / gb:.2f}G "
              f"(budget {hbm}G)", flush=True)

    # the donated jit IS _cholesky_local_scan since the donation lever;
    # the undonated control is a fresh jit of the same traced fn
    probe(f"scan+scanaccum n={n} DONATED", C._cholesky_local_scan,
          spec, uplo="L", nb=nb, use_mxu=True, use_mixed=True)
    undonated = jax.jit(
        C._cholesky_local_scan.__wrapped__,
        static_argnames=("uplo", "nb", "use_mxu", "use_mixed"))
    probe(f"scan+scanaccum n={n} undonated", undonated,
          spec, uplo="L", nb=nb, use_mxu=True, use_mixed=True)

    if args.unrolled:
        probe(f"unrolled-ozaki n={n} DONATED", C._cholesky_local,
              spec, uplo="L", nb=nb, trailing="ozaki")


if __name__ == "__main__":
    main()
